"""Calibration error ECE/MCE (reference functional/classification/calibration_error.py, 365 LoC).

Binned confidence calibration: state = per-bin (conf_sum, acc_sum, count) built
with a single scatter-add — jit-native, constant memory.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_tensor_validation,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _ce_update_binned(confidences: Array, accuracies: Array, n_bins: int) -> Tuple[Array, Array, Array]:
    """One batch's binned-histogram contribution: ``(count, conf_sum, acc_sum)``
    per fixed equal-width bucket, built with a single scatter-add.

    These three ``(n_bins,)`` sums are the WHOLE sufficient statistic for
    ECE/MCE under fixed binning — they add across batches, across lanes and
    across shards (``dist_reduce_fx="sum"``), which is what lets the modular
    metric hold constant-size state instead of a growing sample buffer.
    """
    indices = jnp.clip((confidences * n_bins).astype(jnp.int32), 0, n_bins - 1)
    from torchmetrics_tpu.ops import weighted_bincount_multi

    count, conf, acc = weighted_bincount_multi(
        indices,
        jnp.stack([jnp.ones_like(confidences), confidences, accuracies.astype(jnp.float32)]),
        n_bins,
    )
    return count, conf, acc


def _ce_compute_binned(bin_count: Array, bin_conf: Array, bin_acc: Array, norm: str = "l1") -> Array:
    """Calibration error from accumulated per-bin sums (the binned state)."""
    prop_bin = bin_count / bin_count.sum()
    conf_bin = _safe_divide(bin_conf, bin_count)
    acc_bin = _safe_divide(bin_acc, bin_count)
    if norm == "l1":
        return ((acc_bin - conf_bin).__abs__() * prop_bin).sum()
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin) * (prop_bin > 0))
    if norm == "l2":
        ce = ((acc_bin - conf_bin) ** 2 * prop_bin).sum()
        return jnp.sqrt(ce)
    raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")


def _binning_bucketize(
    confidences: Array, accuracies: Array, bin_boundaries_or_n: int
) -> Tuple[Array, Array, Array]:
    """Per-bin mean confidence, mean accuracy and proportion (reference :36-60)."""
    count, conf, acc = _ce_update_binned(confidences, accuracies, bin_boundaries_or_n)
    prop_bin = count / count.sum()
    return _safe_divide(conf, count), _safe_divide(acc, count), prop_bin


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    n_bins: int,
    norm: str = "l1",
) -> Array:
    # route through the SAME binned sufficient statistic the modular metric
    # accumulates, so the sample-buffer and binned formulations agree up to
    # float summation order
    count, conf, acc = _ce_update_binned(confidences, accuracies, n_bins)
    return _ce_compute_binned(count, conf, acc, norm)


def _binary_calibration_error_arg_validation(n_bins: int, norm: str, ignore_index: Optional[int]) -> None:
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_update(preds: Array, target: Array, valid: Array) -> Tuple[Array, Array]:
    """Returns (confidences, accuracies) with invalid entries mapped to bin-neutral 0.

    Reference semantics (calibration_error.py:136-138): for the binary task the
    confidence is the RAW positive-class probability and the "accuracy" is the
    raw 0/1 target — NOT the top-label max(p, 1-p)/correctness convention
    (which the multiclass task uses). Binning by p vs by max(p, 1-p) groups
    samples into different bins, so the two conventions genuinely differ.
    """
    return jnp.where(valid, preds, 0.0), jnp.where(valid, target == 1, False)


def binary_calibration_error(
    preds: Array,
    target: Array,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary calibration error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_calibration_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_calibration_error(preds, target)
        >>> round(float(result), 4)
        0.425
    """

    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, valid = _binary_confusion_matrix_format(
        preds, target, threshold=0.5, ignore_index=ignore_index, convert_to_labels=False
    )
    import numpy as np

    keep = np.asarray(valid)
    confidences, accuracies = _binary_calibration_error_update(
        jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]), jnp.ones(int(keep.sum()), dtype=bool)
    )
    return _ce_compute(confidences, accuracies, n_bins, norm)


def multiclass_calibration_error(
    preds: Array,
    target: Array,
    num_classes: int,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass calibration error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_calibration_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_calibration_error(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.325
    """

    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    import numpy as np

    from torchmetrics_tpu.functional.classification.stat_scores import _softmax_if_logits

    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).reshape(-1)
    preds = _softmax_if_logits(preds, axis=-1)
    if ignore_index is not None:
        keep = np.asarray(target != ignore_index)
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    confidences = preds.max(-1)
    accuracies = preds.argmax(-1) == target
    return _ce_compute(confidences, accuracies, n_bins, norm)


def calibration_error(
    preds: Array,
    target: Array,
    task: str,
    n_bins: int = 15,
    norm: str = "l1",
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """calibration error (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import calibration_error
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = calibration_error(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.325
    """

    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
