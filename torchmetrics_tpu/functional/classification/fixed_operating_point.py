"""Fixed-operating-point metrics: the `*AtFixed*` quartet.

Covers recall@fixed-precision, precision@fixed-recall, sensitivity@fixed-specificity
and specificity@fixed-sensitivity for all three tasks (reference
functional/classification/{recall_fixed_precision,precision_fixed_recall,
sensitivity_specificity,specificity_sensitivity}.py — four files of per-task Python
loops over zipped curve points).

TPU-first redesign: all four are the SAME reduction — "maximize one curve quantity
subject to another staying above a floor" — so here a single vectorized masked-argmax
kernel (`_best_operating_point`) serves every family. In binned mode it reads the
(T, [C,] 2, 2) confusion-matrix state directly (no intermediate curve materialization)
and is jit-safe with classes vectorized via one `vmap`, where the reference runs a
Python list comprehension per class. Exact mode consumes the host-side curves.

Tie-breaking matches the reference observably: among qualifying points with maximal
objective, the largest threshold wins (the reference reaches the same answer via
lexicographic tuple-max for the PR pair and first-argmax over descending-threshold
curves for the ROC pair). When nothing qualifies — or, for the PR pair, when the best
objective is 0 — the returned threshold is the 1e6 sentinel.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask

_SENTINEL = 1e6


def _best_operating_point(
    objective: Array,
    constraint: Array,
    thresholds: Array,
    min_constraint: float,
    tiebreak: Optional[Array] = None,
    zero_to_sentinel: bool = True,
) -> Tuple[Array, Array]:
    """max(objective) s.t. constraint >= min_constraint, as fixed-shape masked reductions.

    All inputs are threshold-aligned 1-D arrays. Ties on the objective break toward a
    larger ``tiebreak`` value (when given), then toward a larger threshold. Returns
    scalar ``(best_objective, best_threshold)``; the threshold is the 1e6 sentinel when
    nothing qualifies (and, with ``zero_to_sentinel``, when the best objective is 0 —
    the PR-pair convention). Traceable: no data-dependent shapes.
    """
    neg = -jnp.inf
    ok = constraint >= min_constraint
    masked_obj = jnp.where(ok, objective, neg)
    best = jnp.max(masked_obj)
    sel = ok & (masked_obj == best)
    if tiebreak is not None:
        masked_tb = jnp.where(sel, tiebreak, neg)
        sel = sel & (masked_tb == jnp.max(masked_tb))
    best_thr = jnp.max(jnp.where(sel, thresholds, neg))
    any_ok = jnp.any(ok)
    best_val = jnp.where(any_ok, best, 0.0).astype(jnp.float32)
    if zero_to_sentinel:
        best_thr = jnp.where(best_val == 0.0, _SENTINEL, best_thr)
    else:
        best_thr = jnp.where(any_ok, best_thr, _SENTINEL)
    return best_val, best_thr.astype(jnp.float32)


def _binned_pr_quantities(state: Array) -> Tuple[Array, Array]:
    """(precision, recall) per threshold from a (..., T, 2, 2) confmat, threshold-major."""
    tps = state[..., 1, 1]
    fps = state[..., 0, 1]
    fns = state[..., 1, 0]
    return _safe_divide(tps, tps + fps), _safe_divide(tps, tps + fns)


def _binned_roc_quantities(state: Array) -> Tuple[Array, Array]:
    """(sensitivity, specificity) per threshold from a (..., T, 2, 2) confmat.

    Specificity is 1 - fpr (not tns/(tns+fps) directly): with zero negative samples
    the safe-division convention must yield specificity 1, matching the ROC path.
    """
    tps = state[..., 1, 1]
    fps = state[..., 0, 1]
    fns = state[..., 1, 0]
    tns = state[..., 0, 0]
    return _safe_divide(tps, tps + fns), 1.0 - _safe_divide(fps, fps + tns)


# Per family: which curve pair it reads, which quantity it maximizes, whether ties on
# the constraint break before the threshold tie, and whether a 0 objective maps to the
# sentinel threshold (the PR-pair convention) vs only an empty qualifying set (ROC pair).
_FAMILIES = {
    "recall_at_precision": dict(pr_curve=True, tiebreak=True, zero_sentinel=True),
    "precision_at_recall": dict(pr_curve=True, tiebreak=True, zero_sentinel=True),
    "sensitivity_at_specificity": dict(pr_curve=False, tiebreak=False, zero_sentinel=False),
    "specificity_at_sensitivity": dict(pr_curve=False, tiebreak=False, zero_sentinel=False),
}


def _objective_constraint(family: str, precision_or_sens: Array, recall_or_spec: Array) -> Tuple[Array, Array]:
    """Map the family's curve pair onto (objective, constraint).

    Inputs are (precision, recall) for the PR pair and (sensitivity, specificity)
    for the ROC pair, threshold-aligned.
    """
    if family == "recall_at_precision":
        return recall_or_spec, precision_or_sens  # maximize recall s.t. precision floor
    if family == "precision_at_recall":
        return precision_or_sens, recall_or_spec
    if family == "sensitivity_at_specificity":
        return precision_or_sens, recall_or_spec  # maximize sensitivity s.t. specificity floor
    if family == "specificity_at_sensitivity":
        return recall_or_spec, precision_or_sens
    raise ValueError(f"Unknown family {family}")


def _reduce_binned(state: Array, thresholds: Array, min_constraint: float, family: str) -> Tuple[Array, Array]:
    """Binned-mode reduction straight off the (T, 2, 2) or (T, C, 2, 2) state."""
    cfg = _FAMILIES[family]
    quantities = _binned_pr_quantities if cfg["pr_curve"] else _binned_roc_quantities
    first, second = quantities(state)  # threshold-major: (T,) or (T, C)
    objective, constraint = _objective_constraint(family, first, second)
    tiebreak = constraint if cfg["tiebreak"] else None

    def reduce_one(obj, con, tie=None):
        return _best_operating_point(
            obj, con, thresholds, min_constraint, tie, zero_to_sentinel=cfg["zero_sentinel"]
        )

    if state.ndim == 3:  # binary (T, 2, 2)
        return reduce_one(objective, constraint, tiebreak)
    # (T, C, 2, 2): vectorize the reduction over the class axis
    if tiebreak is not None:
        return jax.vmap(reduce_one, in_axes=(1, 1, 1))(objective, constraint, tiebreak)
    return jax.vmap(reduce_one, in_axes=(1, 1))(objective, constraint)


def _reduce_curve(
    curve_a: Array, curve_b: Array, thresholds: Array, min_constraint: float, family: str
) -> Tuple[Array, Array]:
    """Exact-mode reduction over one class's computed curve (host-side, ragged ok).

    ``curve_a``/``curve_b`` are the curve-compute outputs in their natural order:
    (precision, recall) for the PR pair, (fpr, tpr) for the ROC pair. Lengths may
    exceed ``thresholds`` by the synthetic endpoint the PR curve appends; candidates
    are trimmed to the threshold-aligned prefix, exactly as the reference zips them.
    """
    cfg = _FAMILIES[family]
    n = min(curve_a.shape[0], curve_b.shape[0], thresholds.shape[0])
    if cfg["pr_curve"]:
        first, second = curve_a[:n], curve_b[:n]  # precision, recall
    else:
        first, second = curve_b[:n], 1.0 - curve_a[:n]  # sensitivity=tpr, specificity=1-fpr
        # the exact ROC's synthetic (0,0) start point sits above the probability range;
        # report it as threshold 1.0 (preds are probabilities, so only it can exceed 1)
        thresholds = jnp.minimum(thresholds, 1.0)
    objective, constraint = _objective_constraint(family, first, second)
    tiebreak = constraint if cfg["tiebreak"] else None
    return _best_operating_point(
        objective, constraint, thresholds[:n], min_constraint, tiebreak, zero_to_sentinel=cfg["zero_sentinel"]
    )


def _min_constraint_validation(name: str, value: float) -> None:
    if not isinstance(value, float) or not (0 <= value <= 1):
        # deliberate fix of the reference's dead `and` check (recall_fixed_precision.py:85)
        raise ValueError(f"Expected argument `{name}` to be a float in the [0,1] range, but got {value}")


def _binary_fixed_compute(
    state, thresholds: Optional[Array], min_constraint: float, family: str
) -> Tuple[Array, Array]:
    if thresholds is not None and not isinstance(state, tuple):
        return _reduce_binned(state, thresholds, min_constraint, family)
    if _FAMILIES[family]["pr_curve"]:
        p, r, t = _binary_precision_recall_curve_compute(state, None)
        return _reduce_curve(p, r, t, min_constraint, family)
    fpr, tpr, t = _binary_roc_compute(state, None)
    return _reduce_curve(fpr, tpr, t, min_constraint, family)


def _multidim_fixed_compute(
    state, num_classes: int, thresholds: Optional[Array], min_constraint: float, family: str, curves
) -> Tuple[Array, Array]:
    if thresholds is not None and not isinstance(state, tuple):
        return _reduce_binned(state, thresholds, min_constraint, family)
    a_list, b_list, t_list = curves
    res = [
        _reduce_curve(a, b, t, min_constraint, family)
        for a, b, t in zip(a_list, b_list, t_list)
    ]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


# --------------------------------------------------------------------- binary


def _binary_fixed_functional(preds, target, min_constraint, thresholds, ignore_index, validate_args, name, family):
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _min_constraint_validation(name, min_constraint)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, valid, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _binary_fixed_compute(state, thresholds, min_constraint, family)


def binary_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest recall whose precision stays >= ``min_precision`` (reference
    functional/classification/recall_fixed_precision.py:102).

    Returns scalar ``(recall, threshold)``; threshold is 1e6 when unattainable.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.classification import binary_recall_at_fixed_precision
        >>> preds = jnp.asarray([0, 0.5, 0.7, 0.8])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> binary_recall_at_fixed_precision(preds, target, min_precision=0.5, thresholds=5)
        (Array(1., dtype=float32), Array(0.5, dtype=float32))
    """
    return _binary_fixed_functional(
        preds, target, min_precision, thresholds, ignore_index, validate_args,
        "min_precision", "recall_at_precision",
    )


def binary_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest precision whose recall stays >= ``min_recall`` (reference
    functional/classification/precision_fixed_recall.py:63)."""
    return _binary_fixed_functional(
        preds, target, min_recall, thresholds, ignore_index, validate_args,
        "min_recall", "precision_at_recall",
    )


def binary_sensitivity_at_specificity(
    preds: Array,
    target: Array,
    min_specificity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest sensitivity (TPR) whose specificity stays >= ``min_specificity``
    (reference functional/classification/sensitivity_specificity.py:96)."""
    return _binary_fixed_functional(
        preds, target, min_specificity, thresholds, ignore_index, validate_args,
        "min_specificity", "sensitivity_at_specificity",
    )


def binary_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest specificity (TNR) whose sensitivity stays >= ``min_sensitivity``
    (reference functional/classification/specificity_sensitivity.py:96)."""
    return _binary_fixed_functional(
        preds, target, min_sensitivity, thresholds, ignore_index, validate_args,
        "min_sensitivity", "specificity_at_sensitivity",
    )


# ----------------------------------------------------------------- multiclass


def _multiclass_fixed_functional(
    preds, target, num_classes, min_constraint, thresholds, ignore_index, validate_args, name, family
):
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _min_constraint_validation(name, min_constraint)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, valid, num_classes, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    curves = None
    if thresholds is None:
        if _FAMILIES[family]["pr_curve"]:
            p, r, t = _multiclass_precision_recall_curve_compute(state, num_classes, None)
            curves = (p, r, t)
        else:
            fpr, tpr, t = _multiclass_roc_compute(state, num_classes, None)
            curves = (fpr, tpr, t)
    return _multidim_fixed_compute(state, num_classes, thresholds, min_constraint, family, curves)


def multiclass_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest recall with precision >= ``min_precision`` (reference
    functional/classification/recall_fixed_precision.py:206). Returns ``(C,)`` pairs."""
    return _multiclass_fixed_functional(
        preds, target, num_classes, min_precision, thresholds, ignore_index, validate_args,
        "min_precision", "recall_at_precision",
    )


def multiclass_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_classes: int,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest precision with recall >= ``min_recall`` (reference
    functional/classification/precision_fixed_recall.py:138)."""
    return _multiclass_fixed_functional(
        preds, target, num_classes, min_recall, thresholds, ignore_index, validate_args,
        "min_recall", "precision_at_recall",
    )


def multiclass_sensitivity_at_specificity(
    preds: Array,
    target: Array,
    num_classes: int,
    min_specificity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest sensitivity with specificity >= ``min_specificity`` (reference
    functional/classification/sensitivity_specificity.py:199)."""
    return _multiclass_fixed_functional(
        preds, target, num_classes, min_specificity, thresholds, ignore_index, validate_args,
        "min_specificity", "sensitivity_at_specificity",
    )


def multiclass_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_classes: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest specificity with sensitivity >= ``min_sensitivity`` (reference
    functional/classification/specificity_sensitivity.py:199)."""
    return _multiclass_fixed_functional(
        preds, target, num_classes, min_sensitivity, thresholds, ignore_index, validate_args,
        "min_sensitivity", "specificity_at_sensitivity",
    )


# ----------------------------------------------------------------- multilabel


def _multilabel_fixed_functional(
    preds, target, num_labels, min_constraint, thresholds, ignore_index, validate_args, name, family
):
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _min_constraint_validation(name, min_constraint)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, valid, num_labels, thresholds)
    curves = None
    if state is None:
        if _FAMILIES[family]["pr_curve"]:
            curves = _multilabel_precision_recall_curve_compute((preds, target), num_labels, None, ignore_index, valid)
        else:
            curves = _multilabel_roc_compute((preds, target), num_labels, None, valid)
        state = (preds, target)
    return _multidim_fixed_compute(state, num_labels, thresholds, min_constraint, family, curves)


def multilabel_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest recall with precision >= ``min_precision`` (reference
    functional/classification/recall_fixed_precision.py:306)."""
    return _multilabel_fixed_functional(
        preds, target, num_labels, min_precision, thresholds, ignore_index, validate_args,
        "min_precision", "recall_at_precision",
    )


def multilabel_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_labels: int,
    min_recall: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest precision with recall >= ``min_recall`` (reference
    functional/classification/precision_fixed_recall.py:224)."""
    return _multilabel_fixed_functional(
        preds, target, num_labels, min_recall, thresholds, ignore_index, validate_args,
        "min_recall", "precision_at_recall",
    )


def multilabel_sensitivity_at_specificity(
    preds: Array,
    target: Array,
    num_labels: int,
    min_specificity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest sensitivity with specificity >= ``min_specificity`` (reference
    functional/classification/sensitivity_specificity.py:305)."""
    return _multilabel_fixed_functional(
        preds, target, num_labels, min_specificity, thresholds, ignore_index, validate_args,
        "min_specificity", "sensitivity_at_specificity",
    )


def multilabel_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_labels: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest specificity with sensitivity >= ``min_sensitivity`` (reference
    functional/classification/specificity_sensitivity.py:305)."""
    return _multilabel_fixed_functional(
        preds, target, num_labels, min_sensitivity, thresholds, ignore_index, validate_args,
        "min_sensitivity", "specificity_at_sensitivity",
    )


# ---------------------------------------------------------------- dispatchers


def _fixed_dispatch(binary_fn, multiclass_fn, multilabel_fn):
    def dispatcher(
        preds: Array,
        target: Array,
        task: str,
        min_value: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return binary_fn(preds, target, min_value, thresholds, ignore_index, validate_args)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return multiclass_fn(preds, target, num_classes, min_value, thresholds, ignore_index, validate_args)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return multilabel_fn(preds, target, num_labels, min_value, thresholds, ignore_index, validate_args)
        raise ValueError(f"Not handled value: {task}")

    return dispatcher


def recall_at_fixed_precision(
    preds: Array,
    target: Array,
    task: str,
    min_precision: float,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference functional/classification/recall_fixed_precision.py:401).

    Example:
        >>> from torchmetrics_tpu.functional import recall_at_fixed_precision
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = recall_at_fixed_precision(preds, target, task="binary", min_precision=0.5, thresholds=5)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [1.0, 0.25]
    """
    return _fixed_dispatch(
        binary_recall_at_fixed_precision, multiclass_recall_at_fixed_precision, multilabel_recall_at_fixed_precision
    )(preds, target, task, min_precision, thresholds, num_classes, num_labels, ignore_index, validate_args)


def precision_at_fixed_recall(
    preds: Array,
    target: Array,
    task: str,
    min_recall: float,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference functional/classification/precision_fixed_recall.py:309).

    Example:
        >>> from torchmetrics_tpu.functional import precision_at_fixed_recall
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = precision_at_fixed_recall(preds, target, task="binary", min_recall=0.5, thresholds=5)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [1.0, 0.75]
    """
    return _fixed_dispatch(
        binary_precision_at_fixed_recall, multiclass_precision_at_fixed_recall, multilabel_precision_at_fixed_recall
    )(preds, target, task, min_recall, thresholds, num_classes, num_labels, ignore_index, validate_args)


def sensitivity_at_specificity(
    preds: Array,
    target: Array,
    task: str,
    min_specificity: float,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference functional/classification/sensitivity_specificity.py:406).

    Example:
        >>> from torchmetrics_tpu.functional import sensitivity_at_specificity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = sensitivity_at_specificity(preds, target, task="binary", min_specificity=0.5, thresholds=5)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [1.0, 0.25]
    """
    return _fixed_dispatch(
        binary_sensitivity_at_specificity, multiclass_sensitivity_at_specificity, multilabel_sensitivity_at_specificity
    )(preds, target, task, min_specificity, thresholds, num_classes, num_labels, ignore_index, validate_args)


def specificity_at_sensitivity(
    preds: Array,
    target: Array,
    task: str,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference functional/classification/specificity_sensitivity.py:443).

    Example:
        >>> from torchmetrics_tpu.functional import specificity_at_sensitivity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = specificity_at_sensitivity(preds, target, task="binary", min_sensitivity=0.5, thresholds=5)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [1.0, 0.75]
    """
    return _fixed_dispatch(
        binary_specificity_at_sensitivity, multiclass_specificity_at_sensitivity, multilabel_specificity_at_sensitivity
    )(preds, target, task, min_sensitivity, thresholds, num_classes, num_labels, ignore_index, validate_args)
