"""AUROC (reference functional/classification/auroc.py, 480 LoC).

Trapezoidal area under the ROC built from the shared curve state.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _trapz(y: Array, x: Array) -> Array:
    """Trapezoid along the last axis."""
    dx = jnp.diff(x, axis=-1)
    return ((y[..., :-1] + y[..., 1:]) / 2.0 * dx).sum(-1)


def _binary_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    max_fpr: Optional[float] = None,
    pos_label: int = 1,
) -> Array:
    fpr, tpr, _ = _binary_roc_compute(state, thresholds, pos_label)
    # degenerate single-class curves (fpr or tpr identically 0) skip the McClish
    # correction, as the reference does (auroc.py:_binary_auroc_compute)
    if max_fpr is None or max_fpr == 1 or float(jnp.sum(fpr)) == 0 or float(jnp.sum(tpr)) == 0:
        return _trapz(tpr, fpr)
    # McClish correction for partial AUC (reference auroc.py)
    fpr_np, tpr_np = np.asarray(fpr), np.asarray(tpr)
    stop = np.searchsorted(fpr_np, max_fpr, "right")
    x_interp = np.interp(max_fpr, fpr_np[max(stop - 1, 0): stop + 1], tpr_np[max(stop - 1, 0): stop + 1]) if stop < fpr_np.size else tpr_np[-1]
    fpr_c = np.hstack([fpr_np[:stop], [max_fpr]])
    tpr_c = np.hstack([tpr_np[:stop], [x_interp]])
    partial_auc = float(np.trapezoid(tpr_c, fpr_c)) if hasattr(np, "trapezoid") else float(np.trapz(tpr_c, fpr_c))
    min_area = 0.5 * max_fpr**2
    max_area = max_fpr
    return jnp.asarray(0.5 * (1 + (partial_auc - min_area) / (max_area - min_area)), dtype=jnp.float32)


def binary_auroc(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary auroc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_auroc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_auroc(preds, target)
        >>> round(float(result), 4)
        0.75
    """

    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
    preds, target, valid, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _binary_auroc_compute(state, thresholds, max_fpr)


def _reduce_auroc(
    fpr: Union[Array, List[Array]],
    tpr: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Per-class trapezoids then average (reference auroc.py:_reduce_auroc)."""
    if isinstance(fpr, (list, tuple)):
        res = jnp.stack([_trapz(t, f) for f, t in zip(fpr, tpr)])
    else:
        res = _trapz(tpr, fpr)
    if average in (None, "none"):
        return res
    if average == "macro":
        return res.mean()
    if average == "weighted":
        assert weights is not None
        w = _safe_divide(weights.astype(jnp.float32), weights.sum())
        return (res * w).sum()
    raise ValueError(f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}")


def multiclass_auroc(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass auroc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_auroc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_auroc(preds, target, num_classes=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, valid, num_classes, thresholds)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
        target_for_w = state[1]
    else:
        target_for_w = jnp.asarray(np.asarray(target)[np.asarray(valid)])
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    weights = jnp.stack([(target_for_w == c).sum() for c in range(num_classes)]).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights)


def multilabel_auroc(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel auroc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_auroc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_auroc(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, valid, num_labels, thresholds)
    if average == "micro":
        if state is None:
            keep = np.asarray(valid).ravel()
            return _binary_auroc_compute(
                (jnp.asarray(np.asarray(preds).ravel()[keep]), jnp.asarray(np.asarray(target).ravel()[keep])), None
            )
        return _binary_auroc_compute(state.sum(1), thresholds)
    if state is None:
        fpr, tpr, _ = _multilabel_roc_compute((preds, target), num_labels, None, valid)
    else:
        fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds)
    weights = (jnp.asarray(target) * jnp.asarray(valid)).sum(0).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights)


def auroc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """auroc (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import auroc
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = auroc(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        1.0
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
