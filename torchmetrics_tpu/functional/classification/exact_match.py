"""Exact match (reference functional/classification/exact_match.py, 258 LoC).

Multiclass (multidim): a sample counts only if every element matches;
multilabel: a sample counts only if every label matches.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTaskNoBinary


def _exact_match_reduce(correct: Array, total: Array) -> Array:
    return _safe_divide(correct, total)


def _multiclass_exact_match_update(
    preds: Array, target: Array, multidim_average: str = "global", ignore_index: Optional[int] = None
) -> Tuple[Array, Array]:
    """preds/target shaped (N, ...) label tensors."""
    if ignore_index is not None:
        match_or_ignored = (preds == target) | (target == ignore_index)
    else:
        match_or_ignored = preds == target
    correct = match_or_ignored.reshape(match_or_ignored.shape[0], -1).all(axis=1).astype(jnp.int32)
    if multidim_average == "global":
        return correct.sum(), jnp.asarray(correct.shape[0], dtype=jnp.int32)
    return correct, jnp.ones_like(correct)


def multiclass_exact_match(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass exact match (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_exact_match
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_exact_match(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.75
    """

    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, 1, None, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, 1)
    correct, total = _multiclass_exact_match_update(preds, target, multidim_average, ignore_index)
    if multidim_average == "global":
        return _exact_match_reduce(correct, total)
    return correct.astype(jnp.float32)


def _multilabel_exact_match_update(
    preds: Array, target: Array, valid: Array, num_labels: int, multidim_average: str = "global"
) -> Tuple[Array, Array]:
    """preds/target shaped (N, L, ...) thresholded tensors."""
    match_or_ignored = (preds == target) | ~valid
    correct = match_or_ignored.reshape(match_or_ignored.shape[0], num_labels, -1).all(axis=1).astype(jnp.int32)
    if multidim_average == "global":
        return correct.sum(), jnp.asarray(correct.size, dtype=jnp.int32)
    return correct.sum(-1), jnp.asarray(correct.shape[1], dtype=jnp.int32) * jnp.ones(correct.shape[0], dtype=jnp.int32)


def multilabel_exact_match(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel exact match (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_exact_match
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_exact_match(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, None, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, valid = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    correct, total = _multilabel_exact_match_update(preds, target, valid, num_labels, multidim_average)
    return _exact_match_reduce(correct, total)


def exact_match(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """exact match (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import exact_match
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = exact_match(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.75
    """

    task = ClassificationTaskNoBinary.from_str(task)
    if task == ClassificationTaskNoBinary.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_exact_match(preds, target, num_classes, multidim_average, ignore_index, validate_args)
    if task == ClassificationTaskNoBinary.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_exact_match(preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
