"""Precision-recall curve machinery (reference functional/classification/precision_recall_curve.py, 1,008 LoC).

Two state modes, exactly as the reference:

- ``thresholds=None`` → exact curve. All preds/targets accumulate (list states);
  compute sorts + cumsums **eagerly on host** (dynamic output length is illegal
  under jit, and this path is the reference's memory-unbounded mode anyway).
- ``thresholds=int|list|Array`` → binned mode, constant memory. State is a
  ``(T, 2, 2)`` multi-threshold confusion matrix built with one weighted
  scatter-add over ``preds_t + 2*target + 4*arange(T)`` (reference :211-226) —
  a single deterministic TPU kernel, fully jit-native. This is the mode to use
  inside a traced training step.

ROC / AUROC / AveragePrecision reuse this state and post-process.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits, _softmax_if_logits
from torchmetrics_tpu.utils.checks import _check_same_shape, _is_concrete
from torchmetrics_tpu.utils.compute import _safe_divide, interp
from torchmetrics_tpu.utils.enums import ClassificationTask

Thresholds = Union[int, List[float], Array, None]


def _adjust_threshold_arg(thresholds: Thresholds = None) -> Optional[Array]:
    """Convert threshold arg to a tensor of thresholds (reference :104-112)."""
    if thresholds is None:
        return None
    if isinstance(thresholds, int):
        return jnp.linspace(0, 1, thresholds)
    if isinstance(thresholds, (list, tuple)):
        return jnp.asarray(thresholds, dtype=jnp.float32)
    return jnp.asarray(thresholds)


def _binary_precision_recall_curve_arg_validation(
    thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    if thresholds is not None and not isinstance(thresholds, (list, tuple, int)) and not hasattr(thresholds, "shape"):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or tensor of floats,"
            f" but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}")
    if isinstance(thresholds, (list, tuple)) and not all(isinstance(t, float) and 0 <= t <= 1 for t in thresholds):
        raise ValueError(
            f"If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range, but got {thresholds}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _check_binary_target_values(target: Array, ignore_index: Optional[int]) -> None:
    """Target values must be {0, 1} (+ ignore_index) — reference :150-160.

    Data-dependent host check: reads concrete values, so it is skipped
    automatically under jit (same contract as stat_scores validation)."""
    if not _is_concrete(target):
        return
    unique_values = np.unique(np.asarray(target))
    check = (unique_values != 0) & (unique_values != 1)
    if ignore_index is not None:
        check &= unique_values != ignore_index
    if check.any():
        raise ValueError(
            f"Detected the following values in `target`: {unique_values.tolist()} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [ignore_index, 0, 1]}."
        )


def _binary_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating):
        raise ValueError(
            "Expected argument `target` to be an int tensor with ground truth labels,"
            f" but got dtype {jnp.asarray(target).dtype}"
        )
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError(f"Expected argument `preds` to be a float tensor, but got {jnp.asarray(preds).dtype}")
    _check_binary_target_values(target, ignore_index)


def _binary_precision_recall_curve_format(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    """Flatten, sigmoid-if-logits; returns (preds, target, valid_mask, thresholds)."""
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    preds = _sigmoid_if_logits(preds)
    if ignore_index is not None:
        valid = target != ignore_index
        target = jnp.where(valid, target, 0)
    else:
        valid = jnp.ones_like(target, dtype=bool)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target.astype(jnp.int32), valid, thresholds


def _binary_precision_recall_curve_update(
    preds: Array, target: Array, valid: Array, thresholds: Optional[Array]
) -> Optional[Array]:
    """Binned state update: one weighted scatter-add building (T, 2, 2) counts."""
    if thresholds is None:
        return None
    from torchmetrics_tpu.ops import binned_curve_counts

    # fused pallas path on TPU: the (T, N) threshold-compare intermediate
    # never materialises (ops/binned_curve.py)
    return binned_curve_counts(preds, target, valid, thresholds).astype(jnp.int32)


def _binary_clf_curve(
    preds: Array, target: Array, sample_weights: Optional[Array] = None
) -> Tuple[Array, Array, Array]:
    """Exact fps/tps per distinct threshold, host-side (reference :29-81)."""
    preds = np.asarray(preds)
    target = np.asarray(target)
    desc_idx = np.argsort(-preds, kind="stable")
    preds = preds[desc_idx]
    target = target[desc_idx]
    weight = np.asarray(sample_weights)[desc_idx] if sample_weights is not None else 1.0
    distinct_idx = np.nonzero(np.diff(preds))[0]
    threshold_idxs = np.concatenate([distinct_idx, [target.size - 1]])
    tps = np.cumsum(target * weight)[threshold_idxs]
    if sample_weights is not None:
        fps = np.cumsum((1 - target) * weight)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps
    return jnp.asarray(fps), jnp.asarray(tps), jnp.asarray(preds[threshold_idxs])


def _binary_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Compute (precision, recall, thresholds) from binned confmat or raw pair."""
    if thresholds is not None and isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, tuple):
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones(1, dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros(1, dtype=recall.dtype)])
        return precision, recall, thresholds
    preds, target = state
    # host path (eager; dynamic shapes fine): reverse so recall is decreasing,
    # append the (P=1, R=0) endpoint — sklearn>=1.9 / reference semantics
    fps, tps, thresh = (np.asarray(x) for x in _binary_clf_curve(preds, target))
    ps = tps + fps
    precision = np.where(ps != 0, tps / np.where(ps == 0, 1, ps), 0.0)
    recall = tps / tps[-1] if tps.size and tps[-1] != 0 else np.ones_like(tps, dtype=np.float64)
    precision = jnp.asarray(np.hstack([precision[::-1], [1.0]]), dtype=jnp.float32)
    recall = jnp.asarray(np.hstack([recall[::-1], [0.0]]), dtype=jnp.float32)
    thresh = jnp.asarray(thresh[::-1])
    return precision, recall, thresh


def binary_precision_recall_curve(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """Binary PR curve (reference :141+). Returns (precision, recall, thresholds).

    Example:
        >>> from torchmetrics_tpu.functional import binary_precision_recall_curve
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_precision_recall_curve(preds, target)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [[0.5, 0.666700005531311, 0.5, 1.0, 1.0], [1.0, 1.0, 0.5, 0.5, 0.0], [0.19999998807907104, 0.29999998211860657, 0.5999999642372131, 0.7999999523162842]]
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, valid, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    if state is None:
        # exact mode: drop ignored entries on host
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _binary_precision_recall_curve_compute(state, thresholds)


# ----------------------------------------------------------------- multiclass

def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    average: Optional[str] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if average not in (None, "micro", "macro"):
        raise ValueError(f"Expected argument `average` to be one of None, 'micro' or 'macro', but got {average}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_classes={num_classes}`")
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected argument `preds` to be a float tensor with probabilities/logits")
    if jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating):
        raise ValueError("Expected argument `target` to be an int tensor with ground truth labels")
    # class labels must be < num_classes (+ ignore_index) — reference :414-428;
    # value check reads concrete data, skipped under jit
    if _is_concrete(target):
        unique_values = np.unique(np.asarray(target))
        bad = (unique_values < 0) | (unique_values >= num_classes)
        if ignore_index is not None:
            bad &= unique_values != ignore_index
        if bad.any():
            raise ValueError(
                f"Detected values in `target` outside [0, {num_classes - 1}]: "
                f"{unique_values[bad].tolist()}"
            )


def _multiclass_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    average: Optional[str] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).reshape(-1)
    preds = _softmax_if_logits(preds, axis=-1)
    if ignore_index is not None:
        valid = target != ignore_index
        target = jnp.where(valid, target, 0)
    else:
        valid = jnp.ones_like(target, dtype=bool)
    if average == "micro":
        # one-vs-rest flattening: the task becomes binary over N*C pairs
        # (reference precision_recall_curve.py:457-459)
        target = jax.nn.one_hot(target, num_classes, dtype=jnp.int32).reshape(-1)
        valid = jnp.broadcast_to(valid[:, None], (valid.shape[0], num_classes)).reshape(-1)
        preds = preds.reshape(-1)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target.astype(jnp.int32), valid, thresholds


def _multiclass_precision_recall_curve_update(
    preds: Array,
    target: Array,
    valid: Array,
    num_classes: int,
    thresholds: Optional[Array],
    average: Optional[str] = None,
) -> Optional[Array]:
    """Binned state: (T, C, 2, 2) counts via one scatter-add ((T, 2, 2) for micro)."""
    if thresholds is None:
        return None
    if average == "micro":
        return _binary_precision_recall_curve_update(preds, target, valid, thresholds)
    len_t = thresholds.shape[0]
    target_oh = jax.nn.one_hot(target, num_classes, dtype=jnp.float32)  # (N, C)
    if jax.default_backend() not in ("tpu", "axon"):
        # O(N·C·log T) bucketing beats the (T, N, C) materialization off-TPU
        # (bench config 6 history: ops/binned_curve.py)
        from torchmetrics_tpu.ops import binned_curve_counts_classwise

        w = valid.astype(jnp.float32)[:, None]
        counts = binned_curve_counts_classwise(preds, target_oh * w, (1.0 - target_oh) * w, thresholds)
        return counts.astype(jnp.int32)
    preds_t = (preds[None, :, :] >= thresholds[:, None, None]).astype(jnp.int32)  # (T, N, C)
    idx = (
        preds_t
        + 2 * target_oh.astype(jnp.int32)[None, :, :]
        + 4 * jnp.arange(num_classes)[None, None, :]
        + 4 * num_classes * jnp.arange(len_t)[:, None, None]
    )
    w = jnp.broadcast_to(valid.astype(jnp.float32)[None, :, None], idx.shape)
    from torchmetrics_tpu.ops import weighted_bincount

    bins = weighted_bincount(idx.reshape(-1), w.reshape(-1), 4 * num_classes * len_t)
    return bins.reshape(len_t, num_classes, 2, 2).astype(jnp.int32)


def _multiclass_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    average: Optional[str] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if average == "micro":
        return _binary_precision_recall_curve_compute(state, thresholds)
    if thresholds is not None and not isinstance(state, tuple):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_classes), dtype=precision.dtype)], axis=0).T
        recall = jnp.concatenate([recall, jnp.zeros((1, num_classes), dtype=recall.dtype)], axis=0).T
        if average == "macro":
            return _macro_interp_merge(precision, recall, jnp.tile(thresholds, num_classes), descending=False)
        return precision, recall, thresholds
    preds, target = state
    precision_list, recall_list, thresh_list = [], [], []
    for c in range(num_classes):
        p, r, t = _binary_precision_recall_curve_compute(
            (preds[:, c], (target == c).astype(jnp.int32)), None
        )
        precision_list.append(p)
        recall_list.append(r)
        thresh_list.append(t)
    if average == "macro":
        return _macro_interp_merge(precision_list, recall_list, jnp.concatenate(thresh_list), descending=False)
    return precision_list, recall_list, thresh_list


def _macro_interp_merge(xs, ys, all_thresholds: Array, descending: bool):
    """Average per-class curves onto a shared sorted x grid via interpolation
    (reference precision_recall_curve.py:574-588, roc.py:189-201)."""
    num = len(xs)
    thresh = jnp.sort(all_thresholds)
    if descending:
        thresh = jnp.flip(thresh, 0)
    mean_x = jnp.sort(jnp.concatenate([jnp.asarray(x).reshape(-1) for x in xs]))
    mean_y = jnp.zeros_like(mean_x)
    for i in range(num):
        mean_y = mean_y + interp(mean_x, jnp.asarray(xs[i]), jnp.asarray(ys[i]))
    return mean_x, mean_y / num, thresh


def multiclass_precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Multiclass one-vs-rest PR curves (reference :217+).

    ``average``: ``"micro"`` one-hot-flattens into a single binary curve;
    ``"macro"`` interpolation-merges the per-class curves (reference :593-601).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_precision_recall_curve
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_precision_recall_curve(preds, target, num_classes=3, thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(3, 6), (3, 6), (5,)]
    """
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index, average
    )
    state = _multiclass_precision_recall_curve_update(preds, target, valid, num_classes, thresholds, average)
    if state is None:
        keep = np.asarray(valid)
        state = (jnp.asarray(np.asarray(preds)[keep]), jnp.asarray(np.asarray(target)[keep]))
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds, average)


# ----------------------------------------------------------------- multilabel

def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int, thresholds: Thresholds = None, ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_labels={num_labels}`")
    if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating):
        raise ValueError("Expected argument `preds` to be a float tensor with probabilities/logits")
    if jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating):
        raise ValueError("Expected argument `target` to be an int tensor with ground truth labels")
    _check_binary_target_values(target, ignore_index)


def _multilabel_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Optional[Array]]:
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(jnp.asarray(target), 1, -1).reshape(-1, num_labels)
    preds = _sigmoid_if_logits(preds)
    if ignore_index is not None:
        valid = target != ignore_index
        target = jnp.where(valid, target, 0)
    else:
        valid = jnp.ones_like(target, dtype=bool)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target.astype(jnp.int32), valid, thresholds


def _multilabel_precision_recall_curve_update(
    preds: Array, target: Array, valid: Array, num_labels: int, thresholds: Optional[Array]
) -> Optional[Array]:
    if thresholds is None:
        return None
    len_t = thresholds.shape[0]
    if jax.default_backend() not in ("tpu", "axon"):
        from torchmetrics_tpu.ops import binned_curve_counts_classwise

        w = valid.astype(jnp.float32)  # (N, L) per-label mask
        tgt = target.astype(jnp.float32)
        counts = binned_curve_counts_classwise(preds, tgt * w, (1.0 - tgt) * w, thresholds)
        return counts.astype(jnp.int32)
    preds_t = (preds[None, :, :] >= thresholds[:, None, None]).astype(jnp.int32)  # (T, N, L)
    idx = (
        preds_t
        + 2 * target[None, :, :]
        + 4 * jnp.arange(num_labels)[None, None, :]
        + 4 * num_labels * jnp.arange(len_t)[:, None, None]
    )
    w = jnp.broadcast_to(valid.astype(jnp.float32)[None, :, :], idx.shape)
    from torchmetrics_tpu.ops import weighted_bincount

    bins = weighted_bincount(idx.reshape(-1), w.reshape(-1), 4 * num_labels * len_t)
    return bins.reshape(len_t, num_labels, 2, 2).astype(jnp.int32)


def _multilabel_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
    valid: Optional[Array] = None,
):
    if thresholds is not None and not isinstance(state, tuple):
        return _multiclass_precision_recall_curve_compute(state, num_labels, thresholds)
    preds, target = state
    precision_list, recall_list, thresh_list = [], [], []
    for lbl in range(num_labels):
        p_l = np.asarray(preds[:, lbl])
        t_l = np.asarray(target[:, lbl])
        if valid is not None:
            keep = np.asarray(valid[:, lbl])
            p_l, t_l = p_l[keep], t_l[keep]
        p, r, t = _binary_precision_recall_curve_compute((jnp.asarray(p_l), jnp.asarray(t_l)), None)
        precision_list.append(p)
        recall_list.append(r)
        thresh_list.append(t)
    return precision_list, recall_list, thresh_list


def multilabel_precision_recall_curve(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Per-label PR curves (reference :557+).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_precision_recall_curve
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_precision_recall_curve(preds, target, num_labels=3, thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(3, 6), (3, 6), (5,)]
    """
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, valid, num_labels, thresholds)
    if state is None:
        return _multilabel_precision_recall_curve_compute((preds, target), num_labels, None, ignore_index, valid)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds)


def precision_recall_curve(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """precision recall curve (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import precision_recall_curve
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = precision_recall_curve(preds, target, task="binary", thresholds=5)
        >>> [tuple(v.shape) for v in result]
        [(6,), (6,), (5,)]
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_recall_curve(
            preds, target, num_classes, thresholds, ignore_index=ignore_index, validate_args=validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
