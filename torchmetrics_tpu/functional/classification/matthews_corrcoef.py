"""Matthews correlation coefficient (reference functional/classification/matthews_corrcoef.py, 287 LoC)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)
from torchmetrics_tpu.utils.enums import ClassificationTask


def _matthews_corrcoef_reduce(confmat: Array) -> Array:
    """Generalized R_k statistic from a (C, C) confusion matrix (reference :37-78).

    The degenerate ladder mirrors the reference exactly: binary perfect
    (no fp/fn) → 1, binary all-wrong (no tp/tn) → -1, binary zero
    denominator → the eps-regularized estimate, multiclass zero denominator
    → 0. All branches are where-selected so the reduce stays trace-safe.
    """
    if confmat.ndim == 3:  # multilabel (L, 2, 2) → sum into one binary confmat
        confmat = confmat.sum(0)
    confmat = confmat.astype(jnp.float32)
    tk = confmat.sum(1)
    pk = confmat.sum(0)
    c = jnp.trace(confmat)
    s = confmat.sum()
    cov_ytyp = c * s - (tk * pk).sum()
    cov_ypyp = s**2 - (pk * pk).sum()
    cov_ytyt = s**2 - (tk * tk).sum()
    denom = cov_ypyp * cov_ytyt
    general = cov_ytyp / jnp.sqrt(jnp.where(denom == 0, 1.0, denom))
    if confmat.shape[0] != 2:
        return jnp.where(denom == 0, 0.0, general)

    tn, fp, fn, tp = confmat.reshape(-1)
    eps = float(np.finfo(np.float32).eps)
    # reference :66-75 — only the zeroed side contributes to the estimate
    a = jnp.where((tp == 0) | (tn == 0), tp + tn, 0.0)
    b = jnp.where((fp == 0) | (fn == 0), fp + fn, 0.0)
    eps_num = np.sqrt(eps) * (a - b)
    eps_den = (tp + fp + eps) * (tp + fn + eps) * (tn + fp + eps) * (tn + fn + eps)
    mcc = jnp.where(denom == 0, eps_num / jnp.sqrt(eps_den), general)
    mcc = jnp.where((tp + tn != 0) & (fp + fn == 0), 1.0, mcc)
    return jnp.where((tp + tn == 0) & (fp + fn != 0), -1.0, mcc)


def binary_matthews_corrcoef(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """binary matthews corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_matthews_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_matthews_corrcoef(preds, target)
        >>> round(float(result), 4)
        0.0
    """

    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target, valid = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, valid)
    return _matthews_corrcoef_reduce(confmat)


def multiclass_matthews_corrcoef(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multiclass matthews corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_matthews_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_matthews_corrcoef(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.7
    """

    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, valid = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, valid, num_classes)
    return _matthews_corrcoef_reduce(confmat)


def multilabel_matthews_corrcoef(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """multilabel matthews corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_matthews_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_matthews_corrcoef(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize=None)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, valid = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, valid, num_labels)
    return _matthews_corrcoef_reduce(confmat)


def matthews_corrcoef(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """matthews corrcoef (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import matthews_corrcoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = matthews_corrcoef(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.7
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_matthews_corrcoef(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_matthews_corrcoef(preds, target, num_labels, threshold, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
