"""Group-fairness metrics (reference functional/classification/group_fairness.py).

The reference sorts by group id and splits into ragged per-group chunks; here
per-group tp/fp/tn/fn come from one ``segment_sum`` over the group vector —
static shapes, jit-safe, one fused reduction.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
)
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _groups_validation(groups: Array, num_groups: int) -> None:
    """Group tensor must hold ids in [0, num_groups) (reference :30-45).

    The value check runs eagerly only — data-dependent raises cannot trace.
    """
    if not jnp.issubdtype(groups.dtype, jnp.integer):
        raise ValueError(f"Excpected dtype of argument groups to be int, got {groups.dtype}")
    # >= (not the reference's >): out-of-range ids are silently DROPPED by
    # segment_sum here, whereas the reference's sort/split keeps them
    if not isinstance(groups, jax.core.Tracer) and bool(jnp.max(groups) >= num_groups):
        raise ValueError(
            f"The largest number in the groups tensor is {int(jnp.max(groups))}, which is larger than the specified"
            f"number of groups {num_groups}. The group identifiers should be ``0, 1, ..., (num_groups - 1)``."
        )


def _binary_groups_stat_scores(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Per-group (tp, fp, tn, fn), each of shape (num_groups,)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    groups = jnp.asarray(groups)
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)

    preds, target, valid = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    preds = preds.reshape(-1)
    target = target.reshape(-1)
    valid = valid.reshape(-1)
    groups = groups.reshape(-1)

    w = valid.astype(jnp.int32)
    tp = jax.ops.segment_sum(w * (preds & target), groups, num_segments=num_groups)
    fp = jax.ops.segment_sum(w * (preds & (1 - target)), groups, num_segments=num_groups)
    tn = jax.ops.segment_sum(w * ((1 - preds) & (1 - target)), groups, num_segments=num_groups)
    fn = jax.ops.segment_sum(w * ((1 - preds) & target), groups, num_segments=num_groups)
    return tp, fp, tn, fn


def binary_groups_stat_rates(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Per-group (tp, fp, tn, fn) rates normalized by group size (reference :105-163).

    Example:
        >>> from torchmetrics_tpu.functional import binary_groups_stat_rates
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> groups = jnp.asarray([0, 1, 0, 1])
        >>> result = binary_groups_stat_rates(preds, target, groups, num_groups=2)
        >>> {k: jnp.round(v, 4).tolist() for k, v in result.items()}
        {'group_0': [0.0, 0.0, 0.5, 0.5], 'group_1': [0.5, 0.5, 0.0, 0.0]}
    """
    tp, fp, tn, fn = _binary_groups_stat_scores(
        preds, target, groups, num_groups, threshold, ignore_index, validate_args
    )
    stats = jnp.stack([tp, fp, tn, fn], axis=1).astype(jnp.float32)  # (G, 4)
    totals = stats.sum(axis=1, keepdims=True)
    rates = _safe_divide(stats, totals)
    return {f"group_{g}": rates[g] for g in range(num_groups)}


def _compute_binary_demographic_parity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    pos_rates = _safe_divide(tp + fp, tp + fp + tn + fn)
    min_id = int(jnp.argmin(pos_rates))
    max_id = int(jnp.argmax(pos_rates))
    return {f"DP_{min_id}_{max_id}": _safe_divide(pos_rates[min_id], pos_rates[max_id])}


def _compute_binary_equal_opportunity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    tprs = _safe_divide(tp, tp + fn)
    min_id = int(jnp.argmin(tprs))
    max_id = int(jnp.argmax(tprs))
    return {f"EO_{min_id}_{max_id}": _safe_divide(tprs[min_id], tprs[max_id])}


def demographic_parity(
    preds: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """min/max positivity-rate ratio across groups (reference :177-242).

    Example:
        >>> from torchmetrics_tpu.functional import demographic_parity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> groups = jnp.asarray([0, 1, 0, 1])
        >>> result = demographic_parity(preds, groups)
        >>> {k: round(float(v), 4) for k, v in result.items()}
        {'DP_0_1': 0.0}
    """
    return binary_fairness(preds, None, groups, "demographic_parity", threshold, ignore_index, validate_args)


def equal_opportunity(
    preds: Array,
    target: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """min/max true-positive-rate ratio across groups (reference :258+).

    Example:
        >>> from torchmetrics_tpu.functional import equal_opportunity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> groups = jnp.asarray([0, 1, 0, 1])
        >>> result = equal_opportunity(preds, target, groups)
        >>> {k: round(float(v), 4) for k, v in result.items()}
        {'EO_0_1': 0.0}
    """
    return binary_fairness(preds, target, groups, "equal_opportunity", threshold, ignore_index, validate_args)


def binary_fairness(
    preds: Array,
    target: Optional[Array],
    groups: Array,
    task: str = "all",
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity and/or equal opportunity for binary predictions.

    Example:
        >>> from torchmetrics_tpu.functional import binary_fairness
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> groups = jnp.asarray([0, 1, 0, 1])
        >>> result = binary_fairness(preds, target, groups, task="all")
        >>> {k: round(float(v), 4) for k, v in result.items()}
        {'DP_0_1': 0.0, 'EO_0_1': 0.0}
    """
    if task not in ["demographic_parity", "equal_opportunity", "all"]:
        raise ValueError(
            f"Expected argument `task` to either be ``demographic_parity``,"
            f"``equal_opportunity`` or ``all`` but got {task}."
        )
    preds = jnp.asarray(preds)
    if task == "demographic_parity":
        if target is not None:
            rank_zero_warn("The task demographic_parity does not require a target.", UserWarning)
        target = jnp.zeros(preds.shape, dtype=jnp.int32)
    target = jnp.asarray(target)

    # relabel to compact ids so non-contiguous group identifiers keep every sample
    # (segment_sum drops out-of-range ids silently)
    _, groups = jnp.unique(jnp.asarray(groups), return_inverse=True)
    num_groups = int(groups.max()) + 1
    tp, fp, tn, fn = _binary_groups_stat_scores(
        preds, target, groups.astype(jnp.int32), num_groups, threshold, ignore_index, validate_args
    )

    if task == "demographic_parity":
        return _compute_binary_demographic_parity(tp, fp, tn, fn)
    if task == "equal_opportunity":
        return _compute_binary_equal_opportunity(tp, fp, tn, fn)
    return {
        **_compute_binary_demographic_parity(tp, fp, tn, fn),
        **_compute_binary_equal_opportunity(tp, fp, tn, fn),
    }
