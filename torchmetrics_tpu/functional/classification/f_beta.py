"""F-beta / F1 (reference functional/classification/f_beta.py)."""
from __future__ import annotations

from typing import Optional

from jax import Array

from torchmetrics_tpu.functional.classification._stats_helper import (
    _binary_stats,
    _multiclass_stats,
    _multilabel_stats,
)
from torchmetrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utils.enums import ClassificationTask


def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
    top_k: int = 1,
) -> Array:
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average == "micro":
        axis = (0 if multidim_average == "global" else 1) if tp.ndim else None
        tp = tp.sum(axis=axis)
        fn = fn.sum(axis=axis)
        fp = fp.sum(axis=axis)
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    fbeta_score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    return _adjust_weights_safe_divide(fbeta_score, average, multilabel, tp, fp, fn, top_k)


def binary_fbeta_score(
    preds, target, beta: float, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True
):
    """binary fbeta score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_fbeta_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_fbeta_score(preds, target, beta=1.0)
        >>> round(float(result), 4)
        0.5
    """

    if validate_args and (not isinstance(beta, float) or beta <= 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    tp, fp, tn, fn = _binary_stats(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average="binary", multidim_average=multidim_average)


def multiclass_fbeta_score(
    preds, target, beta: float, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True
):
    """multiclass fbeta score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_fbeta_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_fbeta_score(preds, target, beta=1.0, num_classes=3)
        >>> round(float(result), 4)
        0.7778
    """

    if validate_args and (not isinstance(beta, float) or beta <= 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    tp, fp, tn, fn = _multiclass_stats(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average, top_k=top_k)


def multilabel_fbeta_score(
    preds, target, beta: float, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True
):
    """multilabel fbeta score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_fbeta_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_fbeta_score(preds, target, beta=1.0, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    if validate_args and (not isinstance(beta, float) or beta <= 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    tp, fp, tn, fn = _multilabel_stats(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average, multilabel=True)


def binary_f1_score(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True):
    """binary f1 score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import binary_f1_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.8, 0.3, 0.6])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> result = binary_f1_score(preds, target)
        >>> round(float(result), 4)
        0.5
    """

    return binary_fbeta_score(preds, target, 1.0, threshold, multidim_average, ignore_index, validate_args)


def multiclass_f1_score(
    preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True
):
    """multiclass f1 score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multiclass_f1_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = multiclass_f1_score(preds, target, num_classes=3)
        >>> round(float(result), 4)
        0.7778
    """

    return multiclass_fbeta_score(
        preds, target, 1.0, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )


def multilabel_f1_score(
    preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True
):
    """multilabel f1 score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import multilabel_f1_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.8, 0.2, 0.6], [0.4, 0.7, 0.3], [0.1, 0.6, 0.9]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> result = multilabel_f1_score(preds, target, num_labels=3)
        >>> round(float(result), 4)
        1.0
    """

    return multilabel_fbeta_score(
        preds, target, 1.0, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )


def fbeta_score(
    preds,
    target,
    task,
    beta: float = 1.0,
    threshold=0.5,
    num_classes=None,
    num_labels=None,
    average="micro",
    multidim_average="global",
    top_k=1,
    ignore_index=None,
    validate_args=True,
):
    """fbeta score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import fbeta_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = fbeta_score(preds, target, task="multiclass", num_classes=3, beta=1.0)
        >>> round(float(result), 4)
        0.75
    """

    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_fbeta_score(
            preds, target, beta, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fbeta_score(
            preds, target, beta, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


def f1_score(
    preds,
    target,
    task,
    threshold=0.5,
    num_classes=None,
    num_labels=None,
    average="micro",
    multidim_average="global",
    top_k=1,
    ignore_index=None,
    validate_args=True,
):
    """f1 score (functional interface).

    Example:
        >>> from torchmetrics_tpu.functional import f1_score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1], [0.2, 0.2, 0.6], [0.3, 0.4, 0.3]])
        >>> target = jnp.asarray([0, 1, 2, 0])
        >>> result = f1_score(preds, target, task="multiclass", num_classes=3)
        >>> round(float(result), 4)
        0.75
    """

    return fbeta_score(
        preds, target, task, 1.0, threshold, num_classes, num_labels, average, multidim_average, top_k, ignore_index, validate_args
    )
