from torchmetrics_tpu.functional.classification.accuracy import (  # noqa: F401
    accuracy,
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
)
from torchmetrics_tpu.functional.classification.confusion_matrix import (  # noqa: F401
    binary_confusion_matrix,
    confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from torchmetrics_tpu.functional.classification.exact_match import (  # noqa: F401
    exact_match,
    multiclass_exact_match,
    multilabel_exact_match,
)
from torchmetrics_tpu.functional.classification.f_beta import (  # noqa: F401
    binary_f1_score,
    binary_fbeta_score,
    f1_score,
    fbeta_score,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multilabel_f1_score,
    multilabel_fbeta_score,
)
from torchmetrics_tpu.functional.classification.hamming import (  # noqa: F401
    binary_hamming_distance,
    hamming_distance,
    multiclass_hamming_distance,
    multilabel_hamming_distance,
)
from torchmetrics_tpu.functional.classification.jaccard import (  # noqa: F401
    binary_jaccard_index,
    jaccard_index,
    multiclass_jaccard_index,
    multilabel_jaccard_index,
)
from torchmetrics_tpu.functional.classification.precision_recall import (  # noqa: F401
    binary_precision,
    binary_recall,
    multiclass_precision,
    multiclass_recall,
    multilabel_precision,
    multilabel_recall,
    precision,
    recall,
)
from torchmetrics_tpu.functional.classification.specificity import (  # noqa: F401
    binary_specificity,
    multiclass_specificity,
    multilabel_specificity,
    specificity,
)
from torchmetrics_tpu.functional.classification.stat_scores import (  # noqa: F401
    binary_stat_scores,
    multiclass_stat_scores,
    multilabel_stat_scores,
    stat_scores,
)
