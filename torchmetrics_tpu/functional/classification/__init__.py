from torchmetrics_tpu.functional.classification.accuracy import (  # noqa: F401
    accuracy,
    binary_accuracy,
    multiclass_accuracy,
    multilabel_accuracy,
)
from torchmetrics_tpu.functional.classification.confusion_matrix import (  # noqa: F401
    binary_confusion_matrix,
    confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from torchmetrics_tpu.functional.classification.exact_match import (  # noqa: F401
    exact_match,
    multiclass_exact_match,
    multilabel_exact_match,
)
from torchmetrics_tpu.functional.classification.f_beta import (  # noqa: F401
    binary_f1_score,
    binary_fbeta_score,
    f1_score,
    fbeta_score,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multilabel_f1_score,
    multilabel_fbeta_score,
)
from torchmetrics_tpu.functional.classification.hamming import (  # noqa: F401
    binary_hamming_distance,
    hamming_distance,
    multiclass_hamming_distance,
    multilabel_hamming_distance,
)
from torchmetrics_tpu.functional.classification.jaccard import (  # noqa: F401
    binary_jaccard_index,
    jaccard_index,
    multiclass_jaccard_index,
    multilabel_jaccard_index,
)
from torchmetrics_tpu.functional.classification.precision_recall import (  # noqa: F401
    binary_precision,
    binary_recall,
    multiclass_precision,
    multiclass_recall,
    multilabel_precision,
    multilabel_recall,
    precision,
    recall,
)
from torchmetrics_tpu.functional.classification.specificity import (  # noqa: F401
    binary_specificity,
    multiclass_specificity,
    multilabel_specificity,
    specificity,
)
from torchmetrics_tpu.functional.classification.stat_scores import (  # noqa: F401
    binary_stat_scores,
    multiclass_stat_scores,
    multilabel_stat_scores,
    stat_scores,
)
from torchmetrics_tpu.functional.classification.auroc import (  # noqa: F401
    auroc,
    binary_auroc,
    multiclass_auroc,
    multilabel_auroc,
)
from torchmetrics_tpu.functional.classification.average_precision import (  # noqa: F401
    average_precision,
    binary_average_precision,
    multiclass_average_precision,
    multilabel_average_precision,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import (  # noqa: F401
    binary_precision_recall_curve,
    multiclass_precision_recall_curve,
    multilabel_precision_recall_curve,
    precision_recall_curve,
)
from torchmetrics_tpu.functional.classification.roc import (  # noqa: F401
    binary_roc,
    multiclass_roc,
    multilabel_roc,
    roc,
)
from torchmetrics_tpu.functional.classification.calibration_error import (  # noqa: F401
    binary_calibration_error,
    calibration_error,
    multiclass_calibration_error,
)
from torchmetrics_tpu.functional.classification.cohen_kappa import (  # noqa: F401
    binary_cohen_kappa,
    cohen_kappa,
    multiclass_cohen_kappa,
)
from torchmetrics_tpu.functional.classification.hinge import (  # noqa: F401
    binary_hinge_loss,
    hinge_loss,
    multiclass_hinge_loss,
)
from torchmetrics_tpu.functional.classification.matthews_corrcoef import (  # noqa: F401
    binary_matthews_corrcoef,
    matthews_corrcoef,
    multiclass_matthews_corrcoef,
    multilabel_matthews_corrcoef,
)
from torchmetrics_tpu.functional.classification.ranking import (  # noqa: F401
    multilabel_coverage_error,
    multilabel_ranking_average_precision,
    multilabel_ranking_loss,
)
from torchmetrics_tpu.functional.classification.dice import dice  # noqa: F401
from torchmetrics_tpu.functional.classification.group_fairness import (  # noqa: F401
    binary_fairness,
    binary_groups_stat_rates,
    demographic_parity,
    equal_opportunity,
)
from torchmetrics_tpu.functional.classification.fixed_operating_point import (  # noqa: F401
    binary_precision_at_fixed_recall,
    binary_recall_at_fixed_precision,
    binary_sensitivity_at_specificity,
    binary_specificity_at_sensitivity,
    multiclass_precision_at_fixed_recall,
    multiclass_recall_at_fixed_precision,
    multiclass_sensitivity_at_specificity,
    multiclass_specificity_at_sensitivity,
    multilabel_precision_at_fixed_recall,
    multilabel_recall_at_fixed_precision,
    multilabel_sensitivity_at_specificity,
    multilabel_specificity_at_sensitivity,
    precision_at_fixed_recall,
    recall_at_fixed_precision,
    sensitivity_at_specificity,
    specificity_at_sensitivity,
)
