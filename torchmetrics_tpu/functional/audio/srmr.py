"""Speech-to-Reverberation Modulation energy Ratio (SRMR), first-party.

The reference translates SRMRpy to torch but still requires the `gammatone`
and `torchaudio` wheels (reference functional/audio/srmr.py:37-362); SURVEY
§2.16 requires the DSP to be first-party. This module implements the full
pipeline natively:

  gammatone ERB filterbank (Slaney 4-cascade biquads, Glasberg & Moore ERB
  spacing) → Hilbert envelope → 8-channel Q=2 modulation filterbank
  (4..128 Hz) → Hamming-windowed modulation energy (256 ms / 64 ms) →
  energy ratio of low (bands 1-4) to high (bands 5..k*) modulation bands,
  with k* chosen from the 90 %-energy cochlear bandwidth.

Filtering is IIR (sequential over time), so this runs host-side in
float64 numpy/scipy — the natural home for offline speech-quality scoring;
outputs are returned as JAX arrays.
"""
from __future__ import annotations

from math import ceil, pi
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

_EAR_Q = 9.26449  # Glasberg and Moore parameters
_MIN_BW = 24.7


def _centre_freqs(fs: int, num_freqs: int, cutoff: float) -> np.ndarray:
    """ERB-spaced centre frequencies from cutoff to fs/2 (Glasberg & Moore)."""
    low, high = cutoff, fs / 2
    return -(_EAR_Q * _MIN_BW) + np.exp(
        np.arange(1, num_freqs + 1)
        * (-np.log(high + _EAR_Q * _MIN_BW) + np.log(low + _EAR_Q * _MIN_BW))
        / num_freqs
    ) * (high + _EAR_Q * _MIN_BW)


def _calc_erbs(low_freq: float, fs: int, n_filters: int) -> np.ndarray:
    """ERB widths of the filterbank centre frequencies (reference srmr.py:38-46)."""
    cfs = _centre_freqs(fs, n_filters, low_freq)
    return (cfs / _EAR_Q) + _MIN_BW


def _make_erb_filters(fs: int, cfs: np.ndarray) -> np.ndarray:
    """Slaney gammatone filter coefficients, (N, 10) as [A0,A11..A14,A2,B0,B1,B2,gain]."""
    t = 1.0 / fs
    erb = (cfs / _EAR_Q) + _MIN_BW
    b = 1.019 * 2 * np.pi * erb
    arg = 2 * cfs * np.pi * t
    vec = np.exp(2j * arg)

    a0 = t * np.ones_like(cfs)
    a2 = np.zeros_like(cfs)
    b0 = np.ones_like(cfs)
    b1 = -2 * np.cos(arg) / np.exp(b * t)
    b2 = np.exp(-2 * b * t)

    rt_pos = np.sqrt(3 + 2**1.5)
    rt_neg = np.sqrt(3 - 2**1.5)
    common = -t * np.exp(-(b * t))
    k11 = np.cos(arg) + rt_pos * np.sin(arg)
    k12 = np.cos(arg) - rt_pos * np.sin(arg)
    k13 = np.cos(arg) + rt_neg * np.sin(arg)
    k14 = np.cos(arg) - rt_neg * np.sin(arg)

    a11, a12, a13, a14 = common * k11, common * k12, common * k13, common * k14

    gain_arg = np.exp(1j * arg - b * t)
    gain = np.abs(
        (vec - gain_arg * k11)
        * (vec - gain_arg * k12)
        * (vec - gain_arg * k13)
        * (vec - gain_arg * k14)
        * (t * np.exp(b * t) / (-1 / np.exp(b * t) + 1 + vec * (1 - np.exp(b * t)))) ** 4
    )
    return np.column_stack([a0, a11, a12, a13, a14, a2, b0, b1, b2, gain])


def _erb_filterbank(wave: np.ndarray, fcoefs: np.ndarray) -> np.ndarray:
    """Apply the 4-cascade gammatone filterbank: (B, time) -> (B, N, time)."""
    from scipy.signal import lfilter

    gain = fcoefs[:, 9]
    bs = fcoefs[:, 6:9]
    out = np.empty((wave.shape[0], fcoefs.shape[0], wave.shape[1]))
    for i in range(fcoefs.shape[0]):
        a0, a11, a12, a13, a14, a2 = fcoefs[i, 0], fcoefs[i, 1], fcoefs[i, 2], fcoefs[i, 3], fcoefs[i, 4], fcoefs[i, 5]
        y = lfilter([a0, a11, a2], bs[i], wave, axis=-1)
        y = lfilter([a0, a12, a2], bs[i], y, axis=-1)
        y = lfilter([a0, a13, a2], bs[i], y, axis=-1)
        y = lfilter([a0, a14, a2], bs[i], y, axis=-1)
        out[:, i] = y / gain[i]
    return out


def _hilbert_envelope(x: np.ndarray) -> np.ndarray:
    """|analytic signal| along the last axis (reference srmr.py:93-115)."""
    n_orig = x.shape[-1]
    n = n_orig if n_orig % 16 == 0 else ceil(n_orig / 16) * 16
    x_fft = np.fft.fft(x, n=n, axis=-1)
    h = np.zeros(n)
    if n % 2 == 0:
        h[0] = h[n // 2] = 1
        h[1 : n // 2] = 2
    else:
        h[0] = 1
        h[1 : (n + 1) // 2] = 2
    return np.abs(np.fft.ifft(x_fft * h, axis=-1)[..., :n_orig])


def _modulation_filterbank_and_cutoffs(
    min_cf: float, max_cf: float, n: int, fs: float, q: float
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """2nd-order bandpass modulation filters + 3 dB cutoffs (reference srmr.py:58-90)."""
    spacing_factor = (max_cf / min_cf) ** (1.0 / (n - 1))
    cfs = min_cf * spacing_factor ** np.arange(n)

    w0s = 2 * pi * cfs / fs
    mfb = np.zeros((n, 2, 3))
    for k, w0 in enumerate(w0s):
        w0t = np.tan(w0 / 2)
        b0 = w0t / q
        mfb[k, 0] = [b0, 0.0, -b0]
        mfb[k, 1] = [1 + b0 + w0t**2, 2 * w0t**2 - 2, 1 - b0 + w0t**2]

    b0s = np.tan(w0s / 2) / q
    lower = cfs - (b0s * fs / (2 * pi))  # the reference scores against the
    return cfs, mfb, lower  # lower 3 dB cutoffs (srmr.py:78-90,295)


def _normalize_energy(energy: np.ndarray, drange: float = 30.0) -> np.ndarray:
    """Clamp modulation energy into a 30 dB dynamic range (reference srmr.py:150-162)."""
    peak = energy.mean(axis=1, keepdims=True).max(axis=2, keepdims=True).max(axis=3, keepdims=True)
    min_energy = peak * 10.0 ** (-drange / 10.0)
    return np.clip(energy, min_energy, peak)


def _srmr_score(bw: float, avg_energy: np.ndarray, cutoffs: np.ndarray) -> float:
    """Low/high modulation energy ratio with bandwidth-limited k* (reference srmr.py:165-177)."""
    if cutoffs[4] <= bw < cutoffs[5]:
        kstar = 5
    elif cutoffs[5] <= bw < cutoffs[6]:
        kstar = 6
    elif cutoffs[6] <= bw < cutoffs[7]:
        kstar = 7
    elif cutoffs[7] <= bw:
        kstar = 8
    else:
        raise ValueError("Something wrong with the cutoffs compared to bw values.")
    return float(np.sum(avg_energy[:, :4]) / np.sum(avg_energy[:, 4:kstar]))


def _srmr_arg_validate(
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = 128,
    norm: bool = False,
    fast: bool = False,
) -> None:
    """Reference srmr.py:329-362."""
    if not (isinstance(fs, int) and fs > 0):
        raise ValueError(f"Expected argument `fs` to be an int larger than 0, but got {fs}")
    if not (isinstance(n_cochlear_filters, int) and n_cochlear_filters > 0):
        raise ValueError(
            f"Expected argument `n_cochlear_filters` to be an int larger than 0, but got {n_cochlear_filters}"
        )
    if not (isinstance(low_freq, (float, int)) and low_freq > 0):
        raise ValueError(f"Expected argument `low_freq` to be a float larger than 0, but got {low_freq}")
    if not (isinstance(min_cf, (float, int)) and min_cf > 0):
        raise ValueError(f"Expected argument `min_cf` to be a float larger than 0, but got {min_cf}")
    if max_cf is not None and not ((isinstance(max_cf, (float, int))) and max_cf > 0):
        raise ValueError(f"Expected argument `max_cf` to be a float larger than 0, but got {max_cf}")
    if not isinstance(norm, bool):
        raise ValueError("Expected argument `norm` to be a bool value")
    if not isinstance(fast, bool):
        raise ValueError("Expected argument `fast` to be a bool value")


def speech_reverberation_modulation_energy_ratio(
    preds: Array,
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = None,
    norm: bool = False,
    fast: bool = False,
    on_device: bool = False,
) -> Array:
    """Non-intrusive SRMR of ``preds`` with shape ``(..., time)`` (reference srmr.py:179-327).

    ``fast=True`` (SRMRpy's gammatonegram shortcut) is accepted for API parity
    but falls back to the exact filterbank path with a warning. A 1-D input
    returns a shape-(1,) array, matching the reference's documented behaviour
    (srmr.py:228-230: ``tensor([0.3354])``) rather than a scalar.
    ``on_device=True`` runs the jit/vmap-able FIR/FFT pipeline
    (:func:`srmr_on_device`); agreement with the host path ~1e-4 relative.

    Example:
        >>> from torchmetrics_tpu.functional import speech_reverberation_modulation_energy_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = speech_reverberation_modulation_energy_ratio(preds, fs=8000)
        >>> jnp.round(result, 4).tolist()
        [67.73849487304688]
    """
    _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
    if on_device:
        out = srmr_on_device(preds, fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm)
        return jnp.atleast_1d(out) if jnp.ndim(out) == 0 or len(np.shape(preds)) == 1 else out
    if fast:
        import warnings

        warnings.warn(
            "`fast=True` is accepted for API parity but the exact gammatone filterbank path is used.",
            RuntimeWarning,
        )

    shape = np.shape(preds)
    x = np.asarray(preds, dtype=np.float64).reshape(1, -1) if len(shape) == 1 else np.asarray(
        preds, dtype=np.float64
    ).reshape(-1, shape[-1])
    num_batch, time = x.shape

    # normalise into [-1, 1] as the reference does for lfilter stability
    max_vals = np.max(np.abs(x), axis=-1, keepdims=True)
    x = x / np.where(max_vals > 1, max_vals, 1.0)

    w_length = ceil(0.256 * fs)
    w_inc = ceil(0.064 * fs)

    cfs = _centre_freqs(fs, n_cochlear_filters, low_freq)
    fcoefs = _make_erb_filters(fs, cfs)
    gt_env = _hilbert_envelope(_erb_filterbank(x, fcoefs))  # (B, N, time)

    if max_cf is None:
        max_cf = 30 if norm else 128
    _, mfb, cutoffs = _modulation_filterbank_and_cutoffs(min_cf, max_cf, n=8, fs=float(fs), q=2)

    from scipy.signal import lfilter

    num_frames = max(1, int(1 + (time - w_length) // w_inc))  # >=1: pad below covers short signals
    window = np.hamming(w_length + 1)[:-1]
    # (B, N, 8, time) modulation-band envelopes
    mod_out = np.stack(
        [lfilter(mfb[k, 0], mfb[k, 1], gt_env, axis=-1) for k in range(mfb.shape[0])], axis=2
    )
    pad_len = max(ceil(time / w_inc) * w_inc - time, w_length - time)
    mod_out = np.pad(mod_out, [(0, 0)] * 3 + [(0, pad_len)])
    # windowed frame energy sum((x*w)^2) as a sliding dot product of x^2 with
    # w^2 sampled every w_inc — O(time) memory instead of materialising the
    # 4x-overlapping (.., n_frames, w_length) frame tensor
    from scipy.signal import fftconvolve

    sliding = fftconvolve(mod_out**2, (window**2)[None, None, None, ::-1], mode="valid", axes=-1)
    energy = np.maximum(sliding[..., :: w_inc][..., :num_frames], 0.0)  # (B, N, 8, n_frames)

    if norm:
        energy = _normalize_energy(energy)

    erbs = _calc_erbs(low_freq, fs, n_cochlear_filters)[::-1]

    avg_energy = energy.mean(axis=-1)  # (B, N, 8)
    total_energy = avg_energy.reshape(num_batch, -1).sum(axis=-1)
    ac_energy = avg_energy.sum(axis=2)  # (B, N)
    ac_perc = ac_energy * 100 / total_energy[:, None]
    ac_perc_cumsum = np.cumsum(ac_perc[:, ::-1], axis=-1)
    k90perc_idx = np.argmax(ac_perc_cumsum > 90, axis=-1)
    bw = erbs[k90perc_idx]

    scores = np.asarray([_srmr_score(bw[b], avg_energy[b], cutoffs) for b in range(num_batch)])
    out = scores.reshape(shape[:-1]) if len(shape) > 1 else scores
    return jnp.asarray(out, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Device-native (jit/vmap-able) SRMR path
# ---------------------------------------------------------------------------

def _gammatone_fir_taps(fs: int, n_cochlear_filters: int, low_freq: float, length: int) -> np.ndarray:
    """(N, L) FIR approximation of the gammatone bank: its impulse responses.

    Gammatone impulse responses decay as exp(-1.019·2π·ERB·t); at the lowest
    default band (125 Hz) the tail is < -200 dB by 128 ms, so truncation error
    is negligible. Host-computed once per (fs, bank) configuration — static
    under jit.
    """
    cfs = _centre_freqs(fs, n_cochlear_filters, low_freq)
    fcoefs = _make_erb_filters(fs, cfs)
    impulse = np.zeros((1, length))
    impulse[0, 0] = 1.0
    return _erb_filterbank(impulse, fcoefs)[0]  # (N, L)


def _modulation_fir_taps(mfb: np.ndarray, length: int) -> np.ndarray:
    """(8, L) FIR approximation of the Q=2 modulation filters (impulse responses)."""
    from scipy.signal import lfilter

    impulse = np.zeros(length)
    impulse[0] = 1.0
    return np.stack([lfilter(mfb[k, 0], mfb[k, 1], impulse) for k in range(mfb.shape[0])])


def _fft_conv_time(x: Array, taps: Array) -> Array:
    """Causal FIR filtering along the last axis via FFT; output same length as x.

    Broadcasts: x (..., T) with taps (..., L) → (..., T).
    """
    t_len = x.shape[-1]
    l_len = taps.shape[-1]
    n = t_len + l_len - 1
    y = jnp.fft.irfft(jnp.fft.rfft(x, n=n) * jnp.fft.rfft(taps, n=n), n=n)
    return y[..., :t_len]


def _hilbert_envelope_device(x: Array) -> Array:
    """|analytic signal| along the last axis, mirroring the host float path."""
    n_orig = x.shape[-1]
    n = n_orig if n_orig % 16 == 0 else ceil(n_orig / 16) * 16
    x_fft = jnp.fft.fft(x, n=n, axis=-1)
    h = np.zeros(n)
    if n % 2 == 0:
        h[0] = h[n // 2] = 1
        h[1 : n // 2] = 2
    else:
        h[0] = 1
        h[1 : (n + 1) // 2] = 2
    return jnp.abs(jnp.fft.ifft(x_fft * jnp.asarray(h), axis=-1)[..., :n_orig])


def srmr_on_device(
    preds: Array,
    fs: int,
    n_cochlear_filters: int = 23,
    low_freq: float = 125,
    min_cf: float = 4,
    max_cf: Optional[float] = None,
    norm: bool = False,
) -> Array:
    """Device-native SRMR: jit/vmap-able, batched over leading dims.

    The two IIR stages (gammatone bank, modulation filters) are applied as
    host-precomputed FIR impulse responses via FFT convolution — exact to
    truncation (< -60 dB tails) — so the whole pipeline stays on device in
    float32. Agreement with the host float64 path is ~1e-3 relative.
    """
    _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, False)
    shape = preds.shape
    x = jnp.asarray(preds, jnp.float32).reshape(1, -1) if len(shape) == 1 else jnp.asarray(
        preds, jnp.float32
    ).reshape(-1, shape[-1])
    num_batch, time = x.shape

    max_vals = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    x = x / jnp.where(max_vals > 1, max_vals, 1.0)

    w_length = ceil(0.256 * fs)
    w_inc = ceil(0.064 * fs)

    gt_taps = jnp.asarray(_gammatone_fir_taps(fs, n_cochlear_filters, low_freq, int(0.128 * fs)), jnp.float32)
    gt = _fft_conv_time(x[:, None, :], gt_taps[None, :, :])  # (B, N, T)
    gt_env = _hilbert_envelope_device(gt)

    if max_cf is None:
        max_cf = 30 if norm else 128
    _, mfb, cutoffs = _modulation_filterbank_and_cutoffs(min_cf, max_cf, n=8, fs=float(fs), q=2)
    mod_taps = jnp.asarray(_modulation_fir_taps(mfb, int(1.5 * fs)), jnp.float32)
    mod_out = _fft_conv_time(gt_env[:, :, None, :], mod_taps[None, None, :, :])  # (B, N, 8, T)

    num_frames = max(1, int(1 + (time - w_length) // w_inc))
    window = jnp.asarray(np.hamming(w_length + 1)[:-1], jnp.float32)
    pad_len = max(ceil(time / w_inc) * w_inc - time, w_length - time)
    mod_sq = jnp.pad(mod_out**2, [(0, 0)] * 3 + [(0, pad_len)])
    # sliding windowed energy as a correlation with window^2
    w_sq = window**2
    n = mod_sq.shape[-1]
    conv_n = n  # valid part only
    full = jnp.fft.irfft(
        jnp.fft.rfft(mod_sq, n=n + w_length - 1) * jnp.fft.rfft(w_sq[::-1], n=n + w_length - 1),
        n=n + w_length - 1,
    )
    sliding = full[..., w_length - 1 : conv_n]  # 'valid' region
    energy = jnp.maximum(sliding[..., ::w_inc][..., :num_frames], 0.0)

    if norm:
        peak = energy.mean(axis=1, keepdims=True).max(axis=2, keepdims=True).max(axis=3, keepdims=True)
        energy = jnp.clip(energy, peak * 10.0 ** (-3.0), peak)

    erbs = jnp.asarray(_calc_erbs(low_freq, fs, n_cochlear_filters)[::-1].copy(), jnp.float32)

    avg_energy = energy.mean(axis=-1)  # (B, N, 8)
    total_energy = avg_energy.reshape(num_batch, -1).sum(axis=-1)
    ac_energy = avg_energy.sum(axis=2)
    ac_perc = ac_energy * 100 / total_energy[:, None]
    ac_perc_cumsum = jnp.cumsum(ac_perc[:, ::-1], axis=-1)
    k90perc_idx = jnp.argmax(ac_perc_cumsum > 90, axis=-1)
    bw = erbs[k90perc_idx]  # (B,)

    # k* selection without host branching: 5 + #{cutoffs[5:8] <= bw}
    cut = jnp.asarray(cutoffs, jnp.float32)
    kstar = 5 + jnp.sum(bw[:, None] >= cut[None, 5:8], axis=-1)  # (B,)
    band = jnp.arange(8)
    low_e = jnp.sum(jnp.where(band[None, None, :] < 4, avg_energy, 0.0), axis=(1, 2))
    high_mask = (band[None, None, :] >= 4) & (band[None, None, :] < kstar[:, None, None])
    high_e = jnp.sum(jnp.where(high_mask, avg_energy, 0.0), axis=(1, 2))
    scores = low_e / high_e
    return scores.reshape(shape[:-1]) if len(shape) > 1 else scores.astype(jnp.float32)
