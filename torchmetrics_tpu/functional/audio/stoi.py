"""Short-Time Objective Intelligibility (STOI), first-party implementation.

The reference wraps the `pystoi` wheel and runs it on CPU
(reference audio/stoi.py:29-160, functional/audio/stoi.py:24-115); SURVEY
§2.16 requires the DSP to become first-party. This module implements the
complete STOI algorithm (Taal et al. 2011) and the extended variant
(Jensen & Taal 2016) natively:

  resample to 10 kHz → drop silent frames (40 dB dynamic range, 256/128
  Hann framing, overlap-add) → 512-pt STFT → 15 third-octave bands from
  150 Hz → 30-frame segments → (STOI) per-band normalisation + clipping at
  -15 dB SDR then band-row correlation / (ESTOI) row+column normalisation
  and inner product.

Computation is host-side float64 numpy by design — matching the reference,
which also computes STOI on CPU (pystoi is numpy); signals are short and the
metric is eager-only (not differentiable, like the reference's wrapper).
"""
from __future__ import annotations

import warnings
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

FS = 10000
N_FRAME = 256
NFFT = 512
NUMBAND = 15
MINFREQ = 150
N_SEG = 30
BETA = -15.0
DYN_RANGE = 40.0
_EPS = np.finfo(np.float64).eps


def _thirdoct(fs: int, nfft: int, num_bands: int, min_freq: float) -> np.ndarray:
    """Third-octave band matrix over rfft bins (pystoi `thirdoct` semantics)."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands, dtype=np.float64)
    freq_low = min_freq * np.power(2.0, (2 * k - 1) / 6)
    freq_high = min_freq * np.power(2.0, (2 * k + 1) / 6)
    obm = np.zeros((num_bands, len(f)))
    for i in range(num_bands):
        fl_ii = int(np.argmin(np.square(f - freq_low[i])))
        fh_ii = int(np.argmin(np.square(f - freq_high[i])))
        obm[i, fl_ii:fh_ii] = 1.0
    return obm


_OBM = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)
_HANN = np.hanning(N_FRAME + 2)[1:-1]


def _frames(x: np.ndarray, framelen: int, hop: int) -> np.ndarray:
    """Windowed overlapping frames, shape (num_frames, framelen)."""
    n = (len(x) - framelen) // hop + 1
    if n <= 0:
        return np.zeros((0, framelen))
    idx = np.arange(framelen)[None, :] + hop * np.arange(n)[:, None]
    return _HANN[None, :] * x[idx]


def _overlap_and_add(frames: np.ndarray, hop: int) -> np.ndarray:
    num_frames, framelen = frames.shape
    out = np.zeros(framelen + (num_frames - 1) * hop)
    for i in range(num_frames):
        out[i * hop : i * hop + framelen] += frames[i]
    return out


def _remove_silent_frames(
    x: np.ndarray, y: np.ndarray, dyn_range: float, framelen: int, hop: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Drop frames whose clean-signal energy is > dyn_range below the max."""
    x_frames = _frames(x, framelen, hop)
    y_frames = _frames(y, framelen, hop)
    energies = 20 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    mask = (np.max(energies) - dyn_range - energies) < 0
    return _overlap_and_add(x_frames[mask], hop), _overlap_and_add(y_frames[mask], hop)


def _resample_to_fs(x: np.ndarray, fs_in: int) -> np.ndarray:
    """Polyphase resample to 10 kHz (pystoi uses a matlab-style polyphase FIR)."""
    from math import gcd

    from scipy.signal import resample_poly

    g = gcd(FS, fs_in)
    return resample_poly(x, FS // g, fs_in // g)


def _band_envelopes(sig: np.ndarray) -> np.ndarray:
    """(15, num_frames) third-octave band magnitudes of a 10 kHz signal."""
    frames = _frames(sig, N_FRAME, N_FRAME // 2)
    spec = np.fft.rfft(frames, n=NFFT).T  # (freq, frames)
    return np.sqrt(_OBM @ np.square(np.abs(spec)))


def _row_col_normalize(seg: np.ndarray) -> np.ndarray:
    """Normalise band rows then frame columns of (J, 15, 30) segments (ESTOI)."""
    s = seg - np.mean(seg, axis=2, keepdims=True)
    s = s / (np.linalg.norm(s, axis=2, keepdims=True) + _EPS)
    s = s - np.mean(s, axis=1, keepdims=True)
    s = s / (np.linalg.norm(s, axis=1, keepdims=True) + _EPS)
    return s


def _stoi_single(x: np.ndarray, y: np.ndarray, fs: int, extended: bool) -> float:
    """STOI of one clean/degraded pair (pystoi `stoi` pipeline)."""
    if fs != FS:
        x = _resample_to_fs(x, fs)
        y = _resample_to_fs(y, fs)
    if len(x) < N_FRAME:  # shorter than one analysis frame: same path as too-few frames
        warnings.warn(
            "Not enough STFT frames to compute intermediate intelligibility measure after"
            " removing silent frames. Returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5
    x, y = _remove_silent_frames(x, y, DYN_RANGE, N_FRAME, N_FRAME // 2)
    x_tob = _band_envelopes(x)
    y_tob = _band_envelopes(y)
    num_frames = x_tob.shape[1]
    if num_frames < N_SEG:
        warnings.warn(
            "Not enough STFT frames to compute intermediate intelligibility measure after"
            " removing silent frames. Returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5

    # (J, 15, N_SEG) sliding segments
    starts = np.arange(num_frames - N_SEG + 1)
    x_seg = np.stack([x_tob[:, m : m + N_SEG] for m in starts])
    y_seg = np.stack([y_tob[:, m : m + N_SEG] for m in starts])

    if extended:
        x_n = _row_col_normalize(x_seg)
        y_n = _row_col_normalize(y_seg)
        return float(np.sum(x_n * y_n / N_SEG) / x_n.shape[0])

    norm_const = np.linalg.norm(x_seg, axis=2, keepdims=True) / (
        np.linalg.norm(y_seg, axis=2, keepdims=True) + _EPS
    )
    y_prime = np.minimum(y_seg * norm_const, x_seg * (1 + np.power(10.0, -BETA / 20)))

    y_prime = y_prime - np.mean(y_prime, axis=2, keepdims=True)
    x_c = x_seg - np.mean(x_seg, axis=2, keepdims=True)
    y_prime = y_prime / (np.linalg.norm(y_prime, axis=2, keepdims=True) + _EPS)
    x_c = x_c / (np.linalg.norm(x_c, axis=2, keepdims=True) + _EPS)
    J, M = x_c.shape[0], x_c.shape[1]
    return float(np.sum(y_prime * x_c) / (J * M))


def short_time_objective_intelligibility(
    preds: Array,
    target: Array,
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
    on_device: bool = False,
) -> Array:
    """STOI of degraded ``preds`` against clean ``target`` (reference functional/audio/stoi.py:24-115).

    Shapes ``(..., time)``; returns per-signal scores with the batch shape.
    ``on_device=True`` runs the jit/vmap-able float32 pipeline
    (:func:`stoi_on_device`) instead of the host float64 one — agreement ~1e-3.

    Example:
        >>> from torchmetrics_tpu.functional import short_time_objective_intelligibility
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 8000.0)
        >>> target = jnp.sin(2 * jnp.pi * 440 * t)
        >>> preds = target + 0.1 * jnp.sin(2 * jnp.pi * 555 * t)
        >>> result = short_time_objective_intelligibility(preds, target, fs=8000)
        >>> round(float(result), 4)
        0.4694
    """
    if not isinstance(fs, int) or fs <= 0:
        raise ValueError(f"Expected argument `fs` to be a positive integer, but got {fs}")
    if on_device:
        return stoi_on_device(preds, target, fs=fs, extended=extended)
    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.shape != target_np.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds_np.shape} and {target_np.shape}"
        )
    if preds_np.ndim == 1:
        out = np.asarray(_stoi_single(target_np, preds_np, fs, extended))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        vals = [_stoi_single(t, p, fs, extended) for p, t in zip(flat_p, flat_t)]
        out = np.asarray(vals).reshape(preds_np.shape[:-1])
    return jnp.asarray(out, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Device-native (jit/vmap-able) STOI path
# ---------------------------------------------------------------------------

def _resample_taps(up: int, down: int) -> np.ndarray:
    """Static FIR taps replicating scipy.signal.resample_poly's default design."""
    from scipy.signal import firwin

    max_rate = max(up, down)
    half_len = 10 * max_rate
    return firwin(2 * half_len + 1, 1.0 / max_rate, window=("kaiser", 5.0)) * up


def _resample_device(x: Array, up: int, down: int, taps: np.ndarray) -> Array:
    """Polyphase resample of (..., time) on device: zero-stuff → FIR → decimate."""
    n = x.shape[-1]
    up_len = n * up
    xs = jnp.zeros(x.shape[:-1] + (up_len,), x.dtype).at[..., ::up].set(x)
    kernel = jnp.asarray(taps, x.dtype)
    y = jnp.apply_along_axis(lambda row: jnp.convolve(row, kernel, mode="full"), -1, xs) \
        if x.ndim > 1 else jnp.convolve(xs, kernel, mode="full")
    start = len(taps) // 2
    y = y[..., start : start + up_len]
    out_len = -(-n * up) // down if (n * up) % down == 0 else (n * up + down - 1) // down
    return y[..., ::down][..., :out_len]


def _stoi_device_single(x: Array, y: Array, extended: bool) -> Array:
    """Trace-safe STOI of one 10 kHz clean/degraded pair.

    Same math as :func:`_stoi_single`, with the data-dependent silent-frame
    drop re-expressed as a static-shape compaction: frames sort stably by
    validity (argsort of the drop mask), overlap-add runs over the compacted
    grid, and every later stage masks on the valid counts. Short signals fold
    into the ``1e-5`` floor via ``jnp.where`` instead of a host warning.
    """
    hop = N_FRAME // 2
    hann = jnp.asarray(_HANN, x.dtype)
    num_frames = max((x.shape[-1] - N_FRAME) // hop + 1, 0)
    if num_frames == 0:
        return jnp.asarray(1e-5, jnp.float32)
    idx = jnp.arange(N_FRAME)[None, :] + hop * jnp.arange(num_frames)[:, None]
    x_frames = hann[None, :] * x[idx]
    y_frames = hann[None, :] * y[idx]

    energies = 20 * jnp.log10(jnp.linalg.norm(x_frames, axis=1) + _EPS)
    keep = (jnp.max(energies) - DYN_RANGE - energies) < 0
    # stable compaction: valid frames first, original order preserved
    order = jnp.argsort(~keep, stable=True)
    x_frames = x_frames[order]
    y_frames = y_frames[order]
    count = keep.sum()
    slot = jnp.arange(num_frames)
    valid_slot = slot < count
    x_frames = jnp.where(valid_slot[:, None], x_frames, 0.0)
    y_frames = jnp.where(valid_slot[:, None], y_frames, 0.0)

    # overlap-add of the compacted frames (invalid tail adds zeros)
    out_len = N_FRAME + (num_frames - 1) * hop
    pos = idx  # same (frame, offset) grid
    x_sig = jnp.zeros(out_len, x.dtype).at[pos].add(x_frames)
    y_sig = jnp.zeros(out_len, x.dtype).at[pos].add(y_frames)

    # band envelopes over the compacted signal; frames beyond `count` are zero
    spec_idx = idx
    x_tob = jnp.sqrt(jnp.asarray(_OBM, x.dtype) @ jnp.square(jnp.abs(
        jnp.fft.rfft(hann[None, :] * x_sig[spec_idx], n=NFFT).T)))
    y_tob = jnp.sqrt(jnp.asarray(_OBM, x.dtype) @ jnp.square(jnp.abs(
        jnp.fft.rfft(hann[None, :] * y_sig[spec_idx], n=NFFT).T)))

    # sliding (J, 15, N_SEG) segments over the static frame grid
    num_seg = num_frames - N_SEG + 1
    if num_seg <= 0:
        return jnp.asarray(1e-5, jnp.float32)
    starts = jnp.arange(num_seg)
    seg_idx = starts[:, None] + jnp.arange(N_SEG)[None, :]
    x_seg = x_tob[:, seg_idx].transpose(1, 0, 2)
    y_seg = y_tob[:, seg_idx].transpose(1, 0, 2)
    seg_valid = (starts + N_SEG) <= count  # segment fully inside valid frames
    n_valid = seg_valid.sum()

    if extended:
        def _norm(s):
            s = s - jnp.mean(s, axis=2, keepdims=True)
            s = s / (jnp.linalg.norm(s, axis=2, keepdims=True) + _EPS)
            s = s - jnp.mean(s, axis=1, keepdims=True)
            return s / (jnp.linalg.norm(s, axis=1, keepdims=True) + _EPS)

        corr = jnp.sum(_norm(x_seg) * _norm(y_seg), axis=(1, 2)) / N_SEG
        score = jnp.sum(jnp.where(seg_valid, corr, 0.0)) / jnp.maximum(n_valid, 1)
    else:
        norm_const = jnp.linalg.norm(x_seg, axis=2, keepdims=True) / (
            jnp.linalg.norm(y_seg, axis=2, keepdims=True) + _EPS
        )
        y_prime = jnp.minimum(y_seg * norm_const, x_seg * (1 + 10.0 ** (-BETA / 20)))
        y_prime = y_prime - jnp.mean(y_prime, axis=2, keepdims=True)
        x_c = x_seg - jnp.mean(x_seg, axis=2, keepdims=True)
        y_prime = y_prime / (jnp.linalg.norm(y_prime, axis=2, keepdims=True) + _EPS)
        x_c = x_c / (jnp.linalg.norm(x_c, axis=2, keepdims=True) + _EPS)
        corr = jnp.sum(y_prime * x_c, axis=(1, 2)) / x_c.shape[1]
        score = jnp.sum(jnp.where(seg_valid, corr, 0.0)) / jnp.maximum(n_valid, 1)

    return jnp.where(n_valid > 0, score, 1e-5).astype(jnp.float32)


def stoi_on_device(preds: Array, target: Array, fs: int, extended: bool = False) -> Array:
    """Device-native STOI: jit/vmap-able, batched over leading dims.

    Matches the host float64 path (`short_time_objective_intelligibility`) to
    ~1e-3 in float32; use it to keep audio evaluation inside a compiled step.
    """
    if not isinstance(fs, int) or fs <= 0:
        raise ValueError(f"Expected argument `fs` to be a positive integer, but got {fs}")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}"
        )
    if fs != FS:
        from math import gcd

        g = gcd(FS, fs)
        taps = _resample_taps(FS // g, fs // g)
        preds = _resample_device(preds, FS // g, fs // g, taps)
        target = _resample_device(target, FS // g, fs // g, taps)
    if preds.ndim == 1:
        return _stoi_device_single(target, preds, extended)
    flat_p = preds.reshape(-1, preds.shape[-1])
    flat_t = target.reshape(-1, target.shape[-1])
    out = jax.vmap(lambda t, p: _stoi_device_single(t, p, extended))(flat_t, flat_p)
    return out.reshape(preds.shape[:-1])
