"""Short-Time Objective Intelligibility (STOI), first-party implementation.

The reference wraps the `pystoi` wheel and runs it on CPU
(reference audio/stoi.py:29-160, functional/audio/stoi.py:24-115); SURVEY
§2.16 requires the DSP to become first-party. This module implements the
complete STOI algorithm (Taal et al. 2011) and the extended variant
(Jensen & Taal 2016) natively:

  resample to 10 kHz → drop silent frames (40 dB dynamic range, 256/128
  Hann framing, overlap-add) → 512-pt STFT → 15 third-octave bands from
  150 Hz → 30-frame segments → (STOI) per-band normalisation + clipping at
  -15 dB SDR then band-row correlation / (ESTOI) row+column normalisation
  and inner product.

Computation is host-side float64 numpy by design — matching the reference,
which also computes STOI on CPU (pystoi is numpy); signals are short and the
metric is eager-only (not differentiable, like the reference's wrapper).
"""
from __future__ import annotations

import warnings
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

FS = 10000
N_FRAME = 256
NFFT = 512
NUMBAND = 15
MINFREQ = 150
N_SEG = 30
BETA = -15.0
DYN_RANGE = 40.0
_EPS = np.finfo(np.float64).eps


def _thirdoct(fs: int, nfft: int, num_bands: int, min_freq: float) -> np.ndarray:
    """Third-octave band matrix over rfft bins (pystoi `thirdoct` semantics)."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands, dtype=np.float64)
    freq_low = min_freq * np.power(2.0, (2 * k - 1) / 6)
    freq_high = min_freq * np.power(2.0, (2 * k + 1) / 6)
    obm = np.zeros((num_bands, len(f)))
    for i in range(num_bands):
        fl_ii = int(np.argmin(np.square(f - freq_low[i])))
        fh_ii = int(np.argmin(np.square(f - freq_high[i])))
        obm[i, fl_ii:fh_ii] = 1.0
    return obm


_OBM = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)
_HANN = np.hanning(N_FRAME + 2)[1:-1]


def _frames(x: np.ndarray, framelen: int, hop: int) -> np.ndarray:
    """Windowed overlapping frames, shape (num_frames, framelen)."""
    n = (len(x) - framelen) // hop + 1
    if n <= 0:
        return np.zeros((0, framelen))
    idx = np.arange(framelen)[None, :] + hop * np.arange(n)[:, None]
    return _HANN[None, :] * x[idx]


def _overlap_and_add(frames: np.ndarray, hop: int) -> np.ndarray:
    num_frames, framelen = frames.shape
    out = np.zeros(framelen + (num_frames - 1) * hop)
    for i in range(num_frames):
        out[i * hop : i * hop + framelen] += frames[i]
    return out


def _remove_silent_frames(
    x: np.ndarray, y: np.ndarray, dyn_range: float, framelen: int, hop: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Drop frames whose clean-signal energy is > dyn_range below the max."""
    x_frames = _frames(x, framelen, hop)
    y_frames = _frames(y, framelen, hop)
    energies = 20 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    mask = (np.max(energies) - dyn_range - energies) < 0
    return _overlap_and_add(x_frames[mask], hop), _overlap_and_add(y_frames[mask], hop)


def _resample_to_fs(x: np.ndarray, fs_in: int) -> np.ndarray:
    """Polyphase resample to 10 kHz (pystoi uses a matlab-style polyphase FIR)."""
    from math import gcd

    from scipy.signal import resample_poly

    g = gcd(FS, fs_in)
    return resample_poly(x, FS // g, fs_in // g)


def _band_envelopes(sig: np.ndarray) -> np.ndarray:
    """(15, num_frames) third-octave band magnitudes of a 10 kHz signal."""
    frames = _frames(sig, N_FRAME, N_FRAME // 2)
    spec = np.fft.rfft(frames, n=NFFT).T  # (freq, frames)
    return np.sqrt(_OBM @ np.square(np.abs(spec)))


def _row_col_normalize(seg: np.ndarray) -> np.ndarray:
    """Normalise band rows then frame columns of (J, 15, 30) segments (ESTOI)."""
    s = seg - np.mean(seg, axis=2, keepdims=True)
    s = s / (np.linalg.norm(s, axis=2, keepdims=True) + _EPS)
    s = s - np.mean(s, axis=1, keepdims=True)
    s = s / (np.linalg.norm(s, axis=1, keepdims=True) + _EPS)
    return s


def _stoi_single(x: np.ndarray, y: np.ndarray, fs: int, extended: bool) -> float:
    """STOI of one clean/degraded pair (pystoi `stoi` pipeline)."""
    if fs != FS:
        x = _resample_to_fs(x, fs)
        y = _resample_to_fs(y, fs)
    if len(x) < N_FRAME:  # shorter than one analysis frame: same path as too-few frames
        warnings.warn(
            "Not enough STFT frames to compute intermediate intelligibility measure after"
            " removing silent frames. Returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5
    x, y = _remove_silent_frames(x, y, DYN_RANGE, N_FRAME, N_FRAME // 2)
    x_tob = _band_envelopes(x)
    y_tob = _band_envelopes(y)
    num_frames = x_tob.shape[1]
    if num_frames < N_SEG:
        warnings.warn(
            "Not enough STFT frames to compute intermediate intelligibility measure after"
            " removing silent frames. Returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5

    # (J, 15, N_SEG) sliding segments
    starts = np.arange(num_frames - N_SEG + 1)
    x_seg = np.stack([x_tob[:, m : m + N_SEG] for m in starts])
    y_seg = np.stack([y_tob[:, m : m + N_SEG] for m in starts])

    if extended:
        x_n = _row_col_normalize(x_seg)
        y_n = _row_col_normalize(y_seg)
        return float(np.sum(x_n * y_n / N_SEG) / x_n.shape[0])

    norm_const = np.linalg.norm(x_seg, axis=2, keepdims=True) / (
        np.linalg.norm(y_seg, axis=2, keepdims=True) + _EPS
    )
    y_prime = np.minimum(y_seg * norm_const, x_seg * (1 + np.power(10.0, -BETA / 20)))

    y_prime = y_prime - np.mean(y_prime, axis=2, keepdims=True)
    x_c = x_seg - np.mean(x_seg, axis=2, keepdims=True)
    y_prime = y_prime / (np.linalg.norm(y_prime, axis=2, keepdims=True) + _EPS)
    x_c = x_c / (np.linalg.norm(x_c, axis=2, keepdims=True) + _EPS)
    J, M = x_c.shape[0], x_c.shape[1]
    return float(np.sum(y_prime * x_c) / (J * M))


def short_time_objective_intelligibility(
    preds: Array,
    target: Array,
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
) -> Array:
    """STOI of degraded ``preds`` against clean ``target`` (reference functional/audio/stoi.py:24-115).

    Shapes ``(..., time)``; returns per-signal scores with the batch shape.

    Example:
        >>> from torchmetrics_tpu.functional import short_time_objective_intelligibility
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 8000.0)
        >>> target = jnp.sin(2 * jnp.pi * 440 * t)
        >>> preds = target + 0.1 * jnp.sin(2 * jnp.pi * 555 * t)
        >>> result = short_time_objective_intelligibility(preds, target, fs=8000)
        >>> round(float(result), 4)
        0.4694
    """
    if not isinstance(fs, int) or fs <= 0:
        raise ValueError(f"Expected argument `fs` to be a positive integer, but got {fs}")
    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.shape != target_np.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds_np.shape} and {target_np.shape}"
        )
    if preds_np.ndim == 1:
        out = np.asarray(_stoi_single(target_np, preds_np, fs, extended))
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        vals = [_stoi_single(t, p, fs, extended) for p, t in zip(flat_p, flat_t)]
        out = np.asarray(vals).reshape(preds_np.shape[:-1])
    return jnp.asarray(out, dtype=jnp.float32)
