from torchmetrics_tpu.functional.audio.pit import (  # noqa: F401
    permutation_invariant_training,
    pit_permutate,
)
from torchmetrics_tpu.functional.audio.sdr import (  # noqa: F401
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from torchmetrics_tpu.functional.audio.snr import (  # noqa: F401
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)

__all__ = [
    "complex_scale_invariant_signal_noise_ratio",
    "permutation_invariant_training",
    "pit_permutate",
    "scale_invariant_signal_distortion_ratio",
    "scale_invariant_signal_noise_ratio",
    "signal_distortion_ratio",
    "signal_noise_ratio",
    "source_aggregated_signal_distortion_ratio",
]
