from torchmetrics_tpu.functional.audio.pit import (  # noqa: F401
    permutation_invariant_training,
    pit_permutate,
)
from torchmetrics_tpu.functional.audio.sdr import (  # noqa: F401
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality  # noqa: F401
from torchmetrics_tpu.functional.audio.srmr import speech_reverberation_modulation_energy_ratio  # noqa: F401
from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility  # noqa: F401
from torchmetrics_tpu.functional.audio.snr import (  # noqa: F401
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)

__all__ = [
    "complex_scale_invariant_signal_noise_ratio",
    "perceptual_evaluation_speech_quality",
    "short_time_objective_intelligibility",
    "speech_reverberation_modulation_energy_ratio",
    "permutation_invariant_training",
    "pit_permutate",
    "scale_invariant_signal_distortion_ratio",
    "scale_invariant_signal_noise_ratio",
    "signal_distortion_ratio",
    "signal_noise_ratio",
    "source_aggregated_signal_distortion_ratio",
]
