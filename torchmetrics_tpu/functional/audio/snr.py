"""Signal-to-noise ratio family.

Reference behavior: functional/audio/snr.py:22-130 (SNR, SI-SNR, C-SI-SNR).
All three reduce the trailing time axis and return one value per leading index,
so they batch trivially onto the VPU/MXU under jit.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _at_least_float32


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR in dB: ``10*log10(||target||^2 / ||target - preds||^2)``.

    Args:
        preds: estimated signal, shape ``(..., time)``.
        target: reference signal, shape ``(..., time)``.
        zero_mean: subtract the time-axis mean of both signals first.

    Returns:
        SNR values with shape ``(...,)``.

    Example:
        >>> from torchmetrics_tpu.functional import signal_noise_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = signal_noise_ratio(preds, target)
        >>> round(float(result), 4)
        20.0
    """
    # dB outputs keep the f32 dtype contract; f16 sums of squares overflow
    preds = _at_least_float32(preds)
    target = _at_least_float32(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR: SI-SDR with forced zero-mean (reference functional/audio/snr.py:64-88).

    Example:
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = scale_invariant_signal_noise_ratio(preds, target)
        >>> round(float(result), 4)
        20.0
    """
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def complex_scale_invariant_signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """C-SI-SNR over complex STFT inputs (reference functional/audio/snr.py:90-130).

    Accepts complex arrays of shape ``(..., freq, time)`` or real arrays of shape
    ``(..., freq, time, 2)``; flattens the spectral axes and evaluates SI-SDR.

    Example:
        >>> from torchmetrics_tpu.functional import complex_scale_invariant_signal_noise_ratio
        >>> import jax.numpy as jnp
        >>> target = jnp.stack([jnp.cos(jnp.arange(20.0)).reshape(4, 5), jnp.sin(jnp.arange(20.0)).reshape(4, 5)], axis=-1)
        >>> preds = target * 0.9 + 0.01
        >>> result = complex_scale_invariant_signal_noise_ratio(preds, target)
        >>> round(float(result), 4)
        36.0883
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)

    if (preds.ndim < 3 or preds.shape[-1] != 2) or (target.ndim < 3 or target.shape[-1] != 2):
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            f" but got {preds.shape} and {target.shape}."
        )

    preds = preds.reshape(*preds.shape[:-3], -1)
    target = target.reshape(*target.shape[:-3], -1)
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=zero_mean)
