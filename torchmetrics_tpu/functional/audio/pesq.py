"""Perceptual Evaluation of Speech Quality (PESQ), first-party C++ backend.

The reference wraps the `pesq` C wheel (reference functional/audio/pesq.py:24-113);
here the ITU-T P.862 pipeline runs in the first-party native kernel
(``torchmetrics_tpu/native/pesq.cpp``) via ctypes — level alignment, band-limit
filtering, delay estimation, Bark-loudness perceptual model and the
P.862.1/P.862.2 MOS-LQO mapping. See the kernel header for the documented
simplifications (single-utterance alignment, generated Bark tables): their
normalisation is absorbed into per-mode constants solved against
ITU-wheel-computed anchor scores (tools/calibrate_pesq.py), so MOS-LQO values
are pinned to the ITU scale at those anchors (conformance test:
tests/audio/test_dsp.py) and degradation rankings are pinned by property tests.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import Array


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> Array:
    """MOS-LQO of degraded ``preds`` against clean ``target``, shapes ``(..., time)``.

    Reference functional/audio/pesq.py:24-113: same signature; ``n_processes``
    is accepted for parity (the native kernel is already batched).

    Example:
        >>> from torchmetrics_tpu.functional import perceptual_evaluation_speech_quality
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 8000.0)
        >>> target = jnp.sin(2 * jnp.pi * 440 * t)
        >>> preds = target + 0.1 * jnp.sin(2 * jnp.pi * 555 * t)
        >>> result = perceptual_evaluation_speech_quality(preds, target, fs=8000, mode='nb')
        >>> round(float(result), 4)
        4.4069
    """
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    if mode == "wb" and fs == 8000:
        raise ValueError("Argument `mode='wb'` requires `fs=16000`")

    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.shape != target_np.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds_np.shape} and {target_np.shape}"
        )

    from torchmetrics_tpu.native import pesq_batch

    single = preds_np.ndim == 1
    flat_p = preds_np.reshape(1, -1) if single else preds_np.reshape(-1, preds_np.shape[-1])
    flat_t = target_np.reshape(1, -1) if single else target_np.reshape(-1, target_np.shape[-1])
    scores = pesq_batch(flat_t, flat_p, fs, wideband=(mode == "wb"))
    if scores is None:
        raise ModuleNotFoundError(
            "PESQ requires the first-party native kernel, which could not be compiled/loaded"
            " (no C++ toolchain or unusable cache dir — see the RuntimeWarning emitted by"
            " torchmetrics_tpu.native). There is no pure-Python fallback for PESQ."
        )
    out = scores[0] if single else scores.reshape(preds_np.shape[:-1])
    return jnp.asarray(out, dtype=jnp.float32)
