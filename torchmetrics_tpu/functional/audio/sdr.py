"""Signal-to-distortion ratio family.

Reference behavior: functional/audio/sdr.py (SDR via Toeplitz-filter projection,
SI-SDR, SA-SDR). TPU redesign notes:

- The reference builds the symmetric Toeplitz system with ``as_strided`` and
  solves with LAPACK in float64; strided views don't exist in XLA, so the
  Toeplitz matrix is materialised with a static ``|i-j|`` gather (one fused
  XLA gather) and solved batched with ``jnp.linalg.solve`` — one MXU-friendly
  batched solve instead of a per-sample loop.
- Correlations come from rFFT exactly as the reference does; FFT length is a
  static power of two so the kernel caches across steps.
"""
from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.utils.checks import _check_same_shape
from torchmetrics_tpu.utils.compute import _at_least_float32


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix from its first row, shape ``(..., L) -> (..., L, L)``.

    XLA-native equivalent of reference functional/audio/sdr.py:28-53: the strided
    trick becomes a gather on the static index grid ``|i - j|``.
    """
    v_len = vector.shape[-1]
    idx = jnp.abs(jnp.arange(v_len)[:, None] - jnp.arange(v_len)[None, :])
    return vector[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int) -> tuple:
    """FFT auto-correlation of target and cross-correlation with preds.

    Mirrors reference functional/audio/sdr.py:56-87.
    """
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR: energy ratio after projecting preds onto ``filter_length`` shifts of target.

    Reference behavior functional/audio/sdr.py:90-200. ``use_cg_iter`` is accepted
    for API parity; the batched direct solve is already XLA-efficient so conjugate
    gradient is not used.

    Args:
        preds: estimate, shape ``(..., time)``.
        target: reference, shape ``(..., time)``.
        use_cg_iter: ignored (API parity with the reference's fast-bss-eval path).
        filter_length: length of the allowed distortion filter.
        zero_mean: subtract time-axis means first.
        load_diag: optional diagonal loading for ill-conditioned systems.

    Returns:
        SDR values in dB with shape ``(...,)``.

    Example:
        >>> from torchmetrics_tpu.functional import signal_distortion_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = signal_distortion_ratio(preds, target)
        >>> round(float(result), 4)
        21.6639
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)

    # float64 if enabled (jax.config x64), else best available precision
    import jax

    solve_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    preds_dtype = preds.dtype
    preds = preds.astype(solve_dtype)
    target = target.astype(solve_dtype)

    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)
    # clamp the residual energy at dtype resolution: for preds ~= target the float32
    # solve rounds coh to >= 1, which the reference (float64) never hits; this caps
    # SDR at ~10*log10(1/eps) instead of returning inf/nan
    ratio = coh / jnp.clip(1 - coh, jnp.finfo(solve_dtype).eps)
    val = 10.0 * jnp.log10(ratio)
    if jnp.issubdtype(preds_dtype, jnp.floating):
        return val.astype(preds_dtype)
    return val.astype(jnp.float32)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR (reference functional/audio/sdr.py:302-339).

    Example:
        >>> from torchmetrics_tpu.functional import scale_invariant_signal_distortion_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = scale_invariant_signal_distortion_ratio(preds, target)
        >>> round(float(result), 4)
        20.0
    """
    # dB outputs keep the f32 dtype contract; f16 sums of squares overflow
    preds = _at_least_float32(preds)
    target = _at_least_float32(target)
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)


def source_aggregated_signal_distortion_ratio(
    preds: Array,
    target: Array,
    scale_invariant: bool = True,
    zero_mean: bool = False,
) -> Array:
    """SA-SDR over ``(..., spk, time)`` inputs (reference functional/audio/sdr.py:342-430).

    A single alpha scales all speakers, and signal/distortion energies aggregate
    over both speaker and time axes before the dB ratio.

    Example:
        >>> from torchmetrics_tpu.functional import source_aggregated_signal_distortion_ratio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 0.5, 1 / 800.0)
        >>> target = jnp.stack([jnp.sin(2 * jnp.pi * 100 * t), jnp.sin(2 * jnp.pi * 150 * t)])
        >>> preds = target + 0.05 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = source_aggregated_signal_distortion_ratio(preds, target)
        >>> round(float(result), 4)
        26.0254
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    _check_same_shape(preds, target)
    if preds.ndim < 2:
        raise RuntimeError(f"The preds and target should have the shape (..., spk, time), but {preds.shape} found")

    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    if scale_invariant:
        alpha = (jnp.sum(preds * target, axis=(-2, -1), keepdims=True) + eps) / (
            jnp.sum(target**2, axis=(-2, -1), keepdims=True) + eps
        )
        target = alpha * target

    distortion = target - preds
    val = (jnp.sum(target**2, axis=(-2, -1)) + eps) / (jnp.sum(distortion**2, axis=(-2, -1)) + eps)
    return 10 * jnp.log10(val)
