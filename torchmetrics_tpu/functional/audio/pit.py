"""Permutation-invariant training (PIT).

Reference behavior: functional/audio/pit.py:107-230. TPU redesign:

- The reference fills the speaker-pair metric matrix with a Python double loop
  and (for spk>=3) ships it to SciPy's Hungarian solver on the host. Neither
  traces under jit. Here the metric matrix is built with ONE batched metric
  call over the broadcasted speaker grid, and the assignment is solved by
  evaluating all ``spk!`` permutations against the matrix with a static gather
  — fully on-device, no host round-trip, differentiable through best_metric.
- ``spk!`` is static (speaker count is a shape), so the permutation table is a
  compile-time constant; for the practical spk <= 6 this is at most 720 rows.
"""
from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

_ps_cache: dict = {}


def _gen_permutations(spk_num: int) -> np.ndarray:
    """All permutations of ``range(spk_num)`` as a static (perm_num, spk_num) table."""
    if spk_num not in _ps_cache:
        _ps_cache[spk_num] = np.asarray(list(permutations(range(spk_num))), dtype=np.int32)
    return _ps_cache[spk_num]


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Evaluate ``metric_func`` under the best speaker permutation.

    Args:
        preds: estimates, shape ``(batch, spk, ...)``.
        target: references, shape ``(batch, spk, ...)``.
        metric_func: for ``"speaker-wise"``: ``(preds, target) -> (batch,)`` pairwise
            metric; for ``"permutation-wise"``: metric over the full ``(batch, spk, ...)``.
        mode: ``"speaker-wise"`` or ``"permutation-wise"``.
        eval_func: ``"max"`` (higher is better) or ``"min"``.
        kwargs: forwarded to ``metric_func``.

    Returns:
        ``(best_metric, best_perm)`` with shapes ``(batch,)`` and ``(batch, spk)``.

    Example:
        >>> from torchmetrics_tpu.functional import permutation_invariant_training
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> t = jnp.arange(0, 0.5, 1 / 800.0)
        >>> target = jnp.stack([jnp.sin(2 * jnp.pi * 100 * t), jnp.sin(2 * jnp.pi * 150 * t)])[None]
        >>> preds = target[:, ::-1, :] + 0.01 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> result = permutation_invariant_training(preds, target, scale_invariant_signal_noise_ratio)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in result]
        [[40.001399993896484], [[1, 0]]]
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ["speaker-wise", "permutation-wise"]:
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    batch_size, spk_num = target.shape[0:2]
    perms = jnp.asarray(_gen_permutations(spk_num))  # (perm_num, spk)
    perm_num = perms.shape[0]

    if mode == "permutation-wise":
        # evaluate the full-metric on every permuted copy in one batched call
        ppreds = preds[:, perms.reshape(-1), ...].reshape(batch_size * perm_num, *preds.shape[1:])
        ptarget = jnp.repeat(target, perm_num, axis=0)
        metric_of_ps = metric_func(ppreds, ptarget, **kwargs)
        metric_of_ps = jnp.mean(metric_of_ps.reshape(batch_size, perm_num, -1), axis=-1)
        if eval_func == "max":
            best_idx = jnp.argmax(metric_of_ps, axis=1)
            best_metric = jnp.max(metric_of_ps, axis=1)
        else:
            best_idx = jnp.argmin(metric_of_ps, axis=1)
            best_metric = jnp.min(metric_of_ps, axis=1)
        return best_metric, perms[best_idx]

    # speaker-wise: one metric call over the broadcasted (target_idx, preds_idx) grid
    rest = preds.shape[2:]
    p_grid = jnp.broadcast_to(preds[:, None, :, ...], (batch_size, spk_num, spk_num, *rest))
    t_grid = jnp.broadcast_to(target[:, :, None, ...], (batch_size, spk_num, spk_num, *rest))
    metric_mtx = metric_func(
        p_grid.reshape(batch_size * spk_num * spk_num, *rest),
        t_grid.reshape(batch_size * spk_num * spk_num, *rest),
        **kwargs,
    ).reshape(batch_size, spk_num, spk_num)

    if spk_num > 6:
        # spk! explodes past 6 speakers (7! = 5040 rows is fine, 10! is not);
        # solve the assignment on host as the reference does for spk >= 3
        import jax

        if isinstance(metric_mtx, jax.core.Tracer):
            raise ValueError(
                f"speaker-wise PIT with {spk_num} speakers needs the host Hungarian solver, which cannot"
                " run inside jit; call permutation_invariant_training outside a traced context or keep"
                " the speaker count at 6 or below"
            )
        from scipy.optimize import linear_sum_assignment

        mtx = np.asarray(metric_mtx)
        best_perm = np.stack([linear_sum_assignment(m, maximize=eval_func == "max")[1] for m in mtx])
        # mtx[b, t, perm[t]] averaged over t
        best_metric = np.stack([m[np.arange(spk_num), p].mean() for m, p in zip(mtx, best_perm)])
        return jnp.asarray(best_metric), jnp.asarray(best_perm)

    # score every permutation: sum of mtx[t, perm[t]] over t — a static gather
    # (perm_num, spk) indices into the last axis
    scores = jnp.take_along_axis(
        metric_mtx[:, None, :, :],  # (batch, 1, spk_t, spk_p)
        jnp.broadcast_to(perms[None, :, :, None], (batch_size, perm_num, spk_num, 1)),
        axis=-1,
    )[..., 0].mean(axis=-1)  # (batch, perm_num)

    if eval_func == "max":
        best_idx = jnp.argmax(scores, axis=1)
        best_metric = jnp.max(scores, axis=1)
    else:
        best_idx = jnp.argmin(scores, axis=1)
        best_metric = jnp.min(scores, axis=1)
    return best_metric, perms[best_idx]


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` by the per-sample permutation (reference pit.py:216-229).

    Example:
        >>> from torchmetrics_tpu.functional import pit_permutate
        >>> import jax.numpy as jnp
        >>> preds = jnp.arange(12.0).reshape(2, 3, 2)
        >>> perm = jnp.asarray([[1, 0, 2], [0, 2, 1]])
        >>> result = pit_permutate(preds, perm)
        >>> jnp.round(result, 4).tolist()
        [[[2.0, 3.0], [0.0, 1.0], [4.0, 5.0]], [[6.0, 7.0], [10.0, 11.0], [8.0, 9.0]]]
    """
    preds = jnp.asarray(preds)
    perm = jnp.asarray(perm)
    return jnp.take_along_axis(preds, perm.reshape(*perm.shape, *([1] * (preds.ndim - 2))), axis=1)
