"""Durability subsystem: atomic snapshot store, autosave, retry/backoff.

``io.checkpoint`` persists metric state safely (atomic writes, per-leaf
hashes, rotating fallback, preemption flush); ``io.retry`` turns transient
failures into backed-off re-attempts and silent stalls into typed errors.
See docs/DURABILITY.md.
"""
from torchmetrics_tpu.io.checkpoint import (  # noqa: F401
    Autosaver,
    PreemptionHandle,
    atomic_write_bytes,
    install_preemption_handler,
    load_manifest,
    restore_state,
    save_state,
)
from torchmetrics_tpu.io.retry import (  # noqa: F401
    RetryPolicy,
    backoff_delays,
    call_with_retries,
    default_dispatch_deadline,
    default_dispatch_retries,
    default_sync_retries,
    stall_watchdog,
)

__all__ = [
    "Autosaver",
    "PreemptionHandle",
    "atomic_write_bytes",
    "RetryPolicy",
    "backoff_delays",
    "call_with_retries",
    "default_dispatch_deadline",
    "default_dispatch_retries",
    "default_sync_retries",
    "install_preemption_handler",
    "load_manifest",
    "restore_state",
    "save_state",
    "stall_watchdog",
]
