"""Durable metric-state snapshots: atomic writes, validated restores, autosave.

PR 2 contained in-process failures (transactional rollback, validated
``load_state``) and PR 3 made state shardable and resumable — but a SIGTERM,
host crash, or torn write still lost the whole epoch of accumulated metric
state because nothing ever reached disk safely. This module closes the loop
from "contained" to "survivable" (pjit-era training runs assume exactly this:
accumulated state durably checkpointed and restartable, arXiv:2204.06514):

- :func:`save_state` / :func:`restore_state` — a single-file snapshot format
  (versioned manifest + npz payload, per-leaf sha256) written via
  write-to-temp → fsync → atomic rename, so a crash at ANY byte leaves either
  the previous snapshot or none — never a half-written one that parses.
- Rotating stores — ``save_state(..., keep=N)`` keeps the N newest snapshots
  in a directory; ``restore_state`` walks them newest-first and *skips* torn
  or corrupt files (typed :class:`CheckpointCorruptionError`) in favor of the
  newest valid one, never silently installing damage.
- :class:`Autosaver` — cadence-driven snapshots off the hot path: the
  host-side copy reuses the executor's forced-copy recovery snapshot when one
  is fresh (zero extra device sync), and serialization + disk I/O run on a
  background thread.
- :func:`install_preemption_handler` — a SIGTERM/SIGINT hook that flushes one
  final synchronous snapshot before the process dies.

Restores route through the existing ``load_state(validate="strict")`` path,
so every structural/shape/dtype/finiteness guarantee of docs/ROBUSTNESS.md
applies to disk restores too, including stacked sharded (deferred) layouts.

This file is the ONLY place in the package allowed to write state payloads to
disk (enforced by ``tools/lint_atomic_io.py``): one implementation of the
atomic dance means no second, subtly-torn one.
"""
from __future__ import annotations

import hashlib
import io as _io
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.exceptions import (
    CheckpointCorruptionError,
    StateCorruptionError,
    StateDivergenceError,
    TopologyMismatchError,
    TorchMetricsUserError,
)
from torchmetrics_tpu.utils.prints import rank_zero_debug, rank_zero_warn

#: file magic: 10 bytes, includes the container version
_MAGIC = b"TMTPUCKv1\n"

#: manifest schema version (bump on incompatible manifest changes).
#: v2 added the ``topology`` block (docs/DURABILITY.md "Elastic restore");
#: v1 snapshots (no block) still read — see the back-compat shim in
#: ``_check_topology`` and the pinned fixture in tests/fixtures_real/.
MANIFEST_VERSION = 2

#: valid ``restore_state`` topology policies: ``"strict"`` refuses a snapshot
#: whose saved shard layout no longer matches this world
#: (:class:`TopologyMismatchError` — skipped like a torn file in rotating
#: stores); ``"elastic"`` folds/reshards through ``parallel/reshard.py``
TOPOLOGY_POLICIES = ("strict", "elastic")

#: rotating-store snapshot filename pattern
_SNAP_RE = re.compile(r"^snapshot-(\d{8})\.ckpt$")

#: default rotation depth for rotating stores and the Autosaver
DEFAULT_KEEP = 3

#: reserved per-metric export keys (mirrors Metric._RESERVED_STATE_KEYS without
#: importing metric.py at module import time)
_COUNT_KEY = "_update_count"
_SHARDS_KEY = "_sharded_shards"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _leaf_fingerprint(arr: np.ndarray) -> np.ndarray:
    """Pre-save state fingerprint of one export leaf (integrity.py's
    bit-exact uint32[2] fold) — carried in the manifest so the restore path
    can verify the INSTALLED device state, not just the bytes at rest."""
    from torchmetrics_tpu.integrity import host_leaf_fingerprint

    return host_leaf_fingerprint(arr)


def _world_topology() -> Dict[str, Any]:
    """The restoring/saving world's topology descriptor — a module-level seam
    so the chaos harness (``testing/faults.shrink_world``/``grow_world``) can
    simulate a preemption rescheduled onto a different slice shape without a
    real cluster."""
    import jax

    return {
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
    }


def host_copy_tree(state: Dict[str, Any]) -> Dict[str, Any]:
    """Host-side (numpy) deep copy of a state export — the same forced-copy
    discipline as the executor's recovery snapshot (``np.array``, never a
    zero-copy device view a donating dispatch could overwrite). Reserved int
    leaves and list states keep their structure.

    >>> snap = host_copy_tree({"total": np.ones(2), "_update_count": 3})
    >>> snap["_update_count"], snap["total"].shape
    (3, (2,))
    """
    out: Dict[str, Any] = {}
    for k, v in state.items():
        if isinstance(v, dict):
            out[k] = host_copy_tree(v)
        elif isinstance(v, (list, tuple)):
            out[k] = [np.array(el) for el in v]
        elif isinstance(v, (int, float)) and not hasattr(v, "shape"):
            out[k] = v
        else:
            out[k] = np.array(v)
    return out


# ---------------------------------------------------------------- flattening

def _flatten_export(state: Dict[str, Any]) -> Tuple[List[Tuple[Dict[str, Any], np.ndarray]], Dict[str, Any]]:
    """Split a (metric or collection) state export into array leaves + scalars.

    Returns ``(leaves, scalars)``: each leaf is ``(path_descriptor, array)``
    where the descriptor pinpoints the leaf (``leader`` for collections,
    ``field``, ``index`` for list-state elements); ``scalars`` mirrors the
    export's nesting with only the reserved int leaves (counts, shard marks).
    """
    leaves: List[Tuple[Dict[str, Any], np.ndarray]] = []
    scalars: Dict[str, Any] = {}

    def visit(sub: Dict[str, Any], leader: Optional[str]) -> None:
        dst = scalars.setdefault(leader, {}) if leader is not None else scalars
        for field, value in sub.items():
            if isinstance(value, dict):
                if leader is not None:
                    raise TorchMetricsUserError(
                        f"state export nests deeper than collection->metric at {field!r}"
                    )
                visit(value, field)
            elif field in (_COUNT_KEY, _SHARDS_KEY):
                dst[field] = int(np.asarray(value))
            elif isinstance(value, (list, tuple)):
                dst.setdefault("_list_fields", {})[field] = len(value)
                for i, el in enumerate(value):
                    leaves.append(({"leader": leader, "field": field, "index": i}, np.asarray(el)))
            else:
                leaves.append(({"leader": leader, "field": field, "index": None}, np.asarray(value)))

    visit(state, None)
    return leaves, scalars


def _unflatten_export(
    leaves: List[Tuple[Dict[str, Any], np.ndarray]], scalars: Dict[str, Any], nested: bool
) -> Dict[str, Any]:
    """Inverse of :func:`_flatten_export` (list elements arrive in saved order)."""

    def bucket(leader: Optional[str]) -> Dict[str, Any]:
        if not nested:
            return state
        return state.setdefault(leader, {})

    state: Dict[str, Any] = {}
    for desc, arr in leaves:
        dst = bucket(desc["leader"])
        if desc["index"] is None:
            dst[desc["field"]] = arr
        else:
            dst.setdefault(desc["field"], []).append(arr)

    def attach(dst: Dict[str, Any], info: Dict[str, Any]) -> None:
        for field, n in (info.get("_list_fields") or {}).items():
            got = dst.setdefault(field, [])
            if len(got) != n:
                raise obs.flighted(CheckpointCorruptionError(
                    f"list state {field!r} expected {n} elements, payload holds {len(got)}"
                ), domain="checkpoint")
        for key in (_COUNT_KEY, _SHARDS_KEY):
            if key in info:
                dst[key] = int(info[key])

    if nested:
        for leader, info in scalars.items():
            attach(state.setdefault(leader, {}), info or {})
    else:
        attach(state, scalars)
    return state


# ------------------------------------------------------------------- writing

def _snapshot_bytes(obj: Any, state: Dict[str, Any], update_count: Optional[int]) -> bytes:
    """Serialize one snapshot: magic + manifest JSON + npz payload."""
    import jax

    from torchmetrics_tpu import __version__

    nested = any(isinstance(v, dict) for v in state.values())
    leaves, scalars = _flatten_export(state)

    payload_buf = _io.BytesIO()
    arrays = {f"leaf_{i:05d}": arr for i, (_, arr) in enumerate(leaves)}
    np.savez(payload_buf, **arrays)
    payload = payload_buf.getvalue()

    leaf_manifest = [
        {
            "key": f"leaf_{i:05d}",
            "leader": desc["leader"],
            "field": desc["field"],
            "index": desc["index"],
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "sha256": _sha256(np.ascontiguousarray(arr).tobytes()),
            # pre-save state fingerprint (integrity.py): restore_state
            # re-fingerprints the INSTALLED device state against this, so
            # install-path corruption (H2D, aliasing) is caught — the sha256
            # above only ever covers the bytes at rest
            "fingerprint": [int(w) for w in _leaf_fingerprint(arr)],
        }
        for i, (desc, arr) in enumerate(leaves)
    ]

    try:
        spec = obj.state_spec()
    except Exception as err:  # objects without a spec (exotic wrappers) still snapshot
        rank_zero_debug(f"torchmetrics_tpu checkpoint: no state_spec for {type(obj).__name__} ({err})")
        spec = None
    # laned objects (torchmetrics_tpu/lanes.py) describe their occupancy in
    # the manifest so load_manifest can answer "how many sessions does this
    # snapshot hold" without touching the payload arrays
    lanes = None
    try:
        status = getattr(obj, "lane_status", None)
        if isinstance(status, dict):
            lanes = {
                k: status.get(k)
                for k in ("capacity", "active", "compiled", "policy", "quarantined")
                if k in status
            }
    except Exception as err:  # a broken status probe must not block the save
        rank_zero_debug(f"torchmetrics_tpu checkpoint: lane_status probe failed ({err})")

    # windowed objects (torchmetrics_tpu/windows.py) describe their ring in
    # the manifest — window count W, open head slot, and the window clock —
    # so load_manifest answers "which windows does this snapshot hold"
    # without touching the payload arrays
    windows = None
    try:
        spec_fn = getattr(obj, "window_spec", None)
        if spec_fn is None:
            spec_fn = getattr(getattr(obj, "inner", None), "window_spec", None)
        if callable(spec_fn):
            ws = spec_fn()
            if isinstance(ws, dict):
                windows = {
                    k: ws.get(k)
                    for k in ("window", "lateness", "clock", "head", "compiled")
                    if k in ws
                }
    except Exception as err:  # a broken window probe must not block the save
        rank_zero_debug(f"torchmetrics_tpu checkpoint: window_spec probe failed ({err})")

    world = _world_topology()
    # topology block (manifest v2, docs/DURABILITY.md "Elastic restore"): the
    # world shape this snapshot's layout is bound to, so a restore onto a
    # DIFFERENT slice shape is a decision (strict refuse / elastic fold), not
    # an accident. num_shards comes from the reserved shard marks; lane
    # capacity from the lanes block.
    shard_counts = [
        int(sub[_SHARDS_KEY])
        for sub in ([scalars] if not nested else scalars.values())
        if isinstance(sub, dict) and _SHARDS_KEY in sub
    ]
    topology = {
        "topology_version": 1,
        "device_count": world["device_count"],
        "process_count": world["process_count"],
        "mesh_shape": None,  # reserved for explicit mesh-shape binding
        "sharded": bool(shard_counts),
        "num_shards": max(shard_counts) if shard_counts else None,
        "lane_capacity": (lanes or {}).get("capacity"),
        # class-axis placement (parallel/class_shard.py): the shard count the
        # payload's class-stacked fields were saved under, None when every
        # state is dense/replicated along its class axis
        "state_sharding": _class_shard_count_of(obj),
    }
    manifest = {
        "manifest_version": MANIFEST_VERSION,
        "library_version": __version__,
        "jax_version": jax.__version__,
        "created_unix": time.time(),
        "kind": "collection" if nested else "metric",
        "class": type(obj).__name__,
        "spec": spec,
        "lanes": lanes,
        "windows": windows,
        "topology": topology,
        "update_count": update_count,
        "reduce_policy": getattr(obj, "reduce_policy", None),
        "mesh": {
            "device_count": world["device_count"],
            "process_count": world["process_count"],
            "process_index": world["process_index"],
        },
        "scalars": scalars,
        "leaves": leaf_manifest,
        "payload_len": len(payload),
        "payload_sha256": _sha256(payload),
    }
    manifest_bytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
    header = _MAGIC + len(manifest_bytes).to_bytes(8, "little")
    return header + manifest_bytes + payload


def atomic_write_bytes(path: str, data: bytes) -> None:
    """write-to-temp → flush → fsync → atomic rename (+ best-effort dir fsync).

    A crash at any byte leaves either the complete previous file or a stray
    ``.tmp.*`` sibling ``os.replace`` never promoted — the reader can never
    observe a prefix of ``data`` under the final name.

    This is THE durable-write primitive of the package: every on-disk payload
    — state snapshots here, compiled-executable cache entries and shape
    manifests (ops/compile_cache.py) — routes through it, and
    ``tools/lint_atomic_io.py`` flags any other module performing its own
    write/rename dance.
    """
    directory = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(directory, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass  # best-effort temp cleanup; the failure below is the story
        raise
    try:  # the rename itself must be durable, not just the bytes
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        rank_zero_debug(f"torchmetrics_tpu checkpoint: directory fsync unavailable for {directory}")


def _list_snapshots(directory: str) -> List[Tuple[int, str]]:
    """Rotating-store snapshots as (sequence, path), oldest first."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SNAP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def _resolve_update_count(obj: Any, state: Dict[str, Any]) -> Optional[int]:
    if _COUNT_KEY in state:
        return int(np.asarray(state[_COUNT_KEY]))
    counts = [int(np.asarray(v[_COUNT_KEY])) for v in state.values() if isinstance(v, dict) and _COUNT_KEY in v]
    if counts:
        return max(counts)
    count = getattr(obj, "update_count", None)
    return int(count) if count is not None else None


def save_state(
    obj: Any,
    path: str,
    keep: Optional[int] = None,
    states: Optional[Dict[str, Any]] = None,
    sharded: bool = False,
) -> str:
    """Write a durable snapshot of ``obj``'s metric state; returns the path written.

    ``obj`` is a ``Metric`` or ``MetricCollection`` (anything with ``state()``
    / ``state_spec()`` / ``load_state()``). Two addressing modes:

    - ``path`` names a FILE (default): one snapshot, atomically replaced.
    - ``keep=N`` (or ``path`` names an existing directory): a rotating store —
      snapshots are written as ``snapshot-<seq>.ckpt`` inside ``path`` and
      only the N newest are retained. :func:`restore_state` on the directory
      walks them newest-first, skipping torn/corrupt files.

    ``states`` overrides the live state with an external pytree — the
    deferred-reduction epoch loop (``DeferredCollectionStep``) carries its
    accumulated state *outside* the collection, so mid-epoch checkpoints pass
    it here; ``sharded=True`` marks each (leader's) export with the stacked
    shard count so a restore re-installs the per-device layout losslessly
    (``load_state`` auto-detects via the reserved key).

    The write path is crash-atomic (write-to-temp → fsync → rename): a
    preemption mid-save can cost at most the *newest* snapshot, never an old
    valid one.
    """
    with obs.span(obs.SPAN_CKPT_SAVE, owner=type(obj).__name__):
        obs.counter_inc("checkpoint.saves")
        return _save_state_body(obj, path, keep, states, sharded)


def _save_state_body(
    obj: Any,
    path: str,
    keep: Optional[int],
    states: Optional[Dict[str, Any]],
    sharded: bool,
) -> str:
    if states is None:
        export = obj.state()
    else:
        export = {k: (dict(v) if isinstance(v, dict) else v) for k, v in states.items()}
        if sharded:
            def mark(sub: Dict[str, Any]) -> Dict[str, Any]:
                shards = None
                for v in sub.values():
                    arr = np.asarray(v)
                    if arr.ndim >= 1:
                        shards = int(arr.shape[0])
                        break
                if shards is None:
                    raise TorchMetricsUserError("sharded=True but no array leaf carries a shard axis")
                sub = dict(sub)
                sub[_SHARDS_KEY] = shards
                return sub

            if any(isinstance(v, dict) for v in export.values()):
                export = {leader: mark(sub) for leader, sub in export.items()}
            else:
                export = mark(export)
    export = host_copy_tree(export)
    data = _snapshot_bytes(obj, export, _resolve_update_count(obj, export))

    is_dir_store = keep is not None or os.path.isdir(path)
    if not is_dir_store:
        atomic_write_bytes(path, data)
        return path

    keep = DEFAULT_KEEP if keep is None else int(keep)
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    os.makedirs(path, exist_ok=True)
    existing = _list_snapshots(path)
    seq = (existing[-1][0] + 1) if existing else 0
    target = os.path.join(path, f"snapshot-{seq:08d}.ckpt")
    atomic_write_bytes(target, data)
    for _, old in _list_snapshots(path)[:-keep]:
        try:
            os.unlink(old)
        except OSError:
            rank_zero_debug(f"torchmetrics_tpu checkpoint: could not prune {old}")
    return target


# ------------------------------------------------------------------- reading

def load_manifest(path: str) -> Dict[str, Any]:
    """Parse and integrity-check just the manifest of a snapshot file
    (inspection without touching the payload arrays)."""
    manifest, _ = _read_file(path, want_payload=False)
    return manifest


def _read_file(path: str, want_payload: bool = True) -> Tuple[Dict[str, Any], Optional[bytes]]:
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError as err:
        raise obs.flighted(CheckpointCorruptionError(f"cannot read snapshot {path}: {err}"), domain="checkpoint") from err
    if len(blob) < len(_MAGIC) + 8 or not blob.startswith(_MAGIC):
        raise obs.flighted(CheckpointCorruptionError(
            f"{path} is not a torchmetrics_tpu snapshot (bad magic/truncated header)"
        ), domain="checkpoint")
    mlen = int.from_bytes(blob[len(_MAGIC):len(_MAGIC) + 8], "little")
    m_start = len(_MAGIC) + 8
    if mlen <= 0 or m_start + mlen > len(blob):
        raise obs.flighted(CheckpointCorruptionError(f"{path}: manifest length {mlen} exceeds file size (torn write)"), domain="checkpoint")
    try:
        manifest = json.loads(blob[m_start:m_start + mlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as err:
        raise obs.flighted(CheckpointCorruptionError(f"{path}: manifest is not valid JSON ({err})"), domain="checkpoint") from err
    version = manifest.get("manifest_version")
    if not isinstance(version, int) or version > MANIFEST_VERSION:
        raise obs.flighted(CheckpointCorruptionError(
            f"{path}: manifest_version {version!r} unsupported (this build reads <= {MANIFEST_VERSION})"
        ), domain="checkpoint")
    payload = blob[m_start + mlen:]
    if len(payload) != manifest.get("payload_len"):
        raise obs.flighted(CheckpointCorruptionError(
            f"{path}: payload is {len(payload)} bytes, manifest promises"
            f" {manifest.get('payload_len')} (torn write)"
        ), domain="checkpoint")
    if _sha256(payload) != manifest.get("payload_sha256"):
        raise obs.flighted(CheckpointCorruptionError(f"{path}: payload sha256 mismatch (corrupt/torn write)"), domain="checkpoint")
    return manifest, (payload if want_payload else None)


def _decode_state(path: str, manifest: Dict[str, Any], payload: bytes) -> Dict[str, Any]:
    try:
        archive = np.load(_io.BytesIO(payload), allow_pickle=False)
    except Exception as err:
        raise obs.flighted(CheckpointCorruptionError(f"{path}: payload archive unreadable ({err})"), domain="checkpoint") from err
    leaves: List[Tuple[Dict[str, Any], np.ndarray]] = []
    for entry in manifest.get("leaves", []):
        key = entry["key"]
        if key not in archive.files:
            raise obs.flighted(CheckpointCorruptionError(f"{path}: payload missing leaf {key} ({entry['field']!r})"), domain="checkpoint")
        arr = archive[key]
        if list(arr.shape) != entry["shape"] or str(arr.dtype) != entry["dtype"]:
            raise obs.flighted(CheckpointCorruptionError(
                f"{path}: leaf {entry['field']!r} is {arr.dtype}{tuple(arr.shape)},"
                f" manifest promises {entry['dtype']}{tuple(entry['shape'])}"
            ), domain="checkpoint")
        if _sha256(np.ascontiguousarray(arr).tobytes()) != entry["sha256"]:
            raise obs.flighted(CheckpointCorruptionError(
                f"{path}: leaf {entry['field']!r} sha256 mismatch (bit rot / corrupt write)"
            ), domain="checkpoint")
        leaves.append(({"leader": entry["leader"], "field": entry["field"], "index": entry["index"]}, arr))
    return _unflatten_export(leaves, manifest.get("scalars") or {}, manifest.get("kind") == "collection")


def _class_shard_count_of(obj: Any) -> Optional[int]:
    """The class-axis shard count of ``obj``'s state layout (metric or
    collection member), or None when no field is class-sharded — the value
    the manifest topology block binds a snapshot to."""

    def probe(m: Any) -> Optional[int]:
        layouts = getattr(m, "_class_layouts", None) or {}
        counts = [int(lay.num_shards) for lay in layouts.values()]
        return max(counts) if counts else None

    count = probe(obj)
    if count is not None:
        return count
    for member in (getattr(obj, "_modules", None) or {}).values():
        count = probe(member)
        if count is not None:
            return count
    return None


def _check_topology(path: str, manifest: Dict[str, Any], obj: Any, topology: str) -> str:
    """Compare the snapshot's saved topology block against the restoring
    world; returns the action taken (``"match"``/``"legacy"``/``"fold"``/
    ``"remap"``). Under ``topology="strict"`` a shard-layout mismatch raises
    :class:`TopologyMismatchError` (a rotating-store scan skips it like a
    torn file and tries the next older snapshot). Lane capacity is NOT a
    strict gate: a laned restore has always re-registered the snapshot's
    capacity (docs/LANES.md "Durability"); elastic mode instead REMAPS the
    directory into the instance's configured capacity."""
    saved = manifest.get("topology")
    if saved is None:
        # pre-topology-block snapshot (manifest v1): restore proceeds — old
        # checkpoints must keep reading across manifest bumps — but the
        # missing validation is logged, not silent
        obs.counter_inc("checkpoint.legacy_topology_reads")
        rank_zero_warn(
            f"torchmetrics_tpu checkpoint: {path} predates the topology block"
            " (manifest v1); restoring without topology validation —"
            " re-save to bind the snapshot to its world shape"
        )
        return "legacy"
    world = _world_topology()
    if saved.get("sharded") and saved.get("num_shards") and saved["num_shards"] != world["device_count"]:
        if topology == "strict":
            obs.counter_inc("checkpoint.topology_mismatches")
            obs.fault_breadcrumb(
                "topology_mismatch",
                domain="checkpoint",
                data={
                    "snapshot": os.path.basename(path),
                    "saved_num_shards": saved["num_shards"],
                    "device_count": world["device_count"],
                },
            )
            raise obs.flighted(TopologyMismatchError(
                f"{path} holds a {saved['num_shards']}-shard stacked state but this world"
                f" has {world['device_count']} device(s); restore with topology='elastic'"
                " to fold to the topology-neutral form, or restore on the saved topology",
                saved=saved,
                current=world,
            ), domain="checkpoint")
        return "fold"
    saved_class_shards = saved.get("state_sharding")
    current_class_shards = _class_shard_count_of(obj)
    if saved_class_shards != current_class_shards:
        if topology == "strict":
            obs.counter_inc("checkpoint.topology_mismatches")
            obs.fault_breadcrumb(
                "topology_mismatch",
                domain="checkpoint",
                data={
                    "snapshot": os.path.basename(path),
                    "saved_class_shards": saved_class_shards,
                    "class_shards": current_class_shards,
                },
            )
            saved_desc = (
                f"class-sharded state saved under {saved_class_shards} class shard(s)"
                if saved_class_shards
                else "a dense (replicated) class layout"
            )
            current_desc = (
                f"{current_class_shards} class shard(s)"
                if current_class_shards
                else "a dense (replicated) class layout"
            )
            raise obs.flighted(TopologyMismatchError(
                f"{path} holds {saved_desc} but this instance is laid out for"
                f" {current_desc}; restore with topology='elastic' to"
                " re-split through the layout seam, or restore on the saved layout",
                saved=saved,
                current={"class_shards": current_class_shards},
            ), domain="checkpoint")
        # elastic: load_state's class-layout adoption re-splits exactly
        # (gather to dense + re-stack, parallel/class_shard.py) — no fold
        # needed, but the restore is counted as elastic
        return "reshard"
    lane_cap = saved.get("lane_capacity")
    if (
        topology == "elastic"
        and lane_cap is not None
        and getattr(obj, "capacity", None) not in (None, lane_cap)
    ):
        return "remap"
    return "match"


def _force_fold(obj: Any) -> None:
    """Collapse any pending sharded install to the canonical (reduced) form
    NOW — the elastic restore's eager fold (lazy folding would otherwise hide
    the reshard until the next update/compute)."""
    fold = getattr(obj, "_fold_pending", None)
    if callable(fold):
        fold()
        return
    for member in (getattr(obj, "_modules", None) or {}).values():
        member_fold = getattr(member, "_fold_pending", None)
        if callable(member_fold):
            member_fold()


def _verify_installed_state(path: str, manifest: Dict[str, Any], obj: Any) -> None:
    """Re-fingerprint the state ``obj`` installed and compare per-leaf
    against the manifest's pre-save fingerprints (where present — older
    snapshots verify vacuously). Leaves whose installed shape/dtype differ
    from the saved ones (a ``validate="cast"`` conversion, a grown buffer)
    are legitimately transformed and skipped. A mismatch on an unchanged
    leaf is install-path corruption: breadcrumb + counter + flighted
    :class:`StateDivergenceError` — a :class:`StateCorruptionError`
    subclass, so a rotating-store scan falls back to the next older
    snapshot exactly as for a torn file."""
    entries = {
        (e.get("leader"), e.get("field"), e.get("index")): e
        for e in manifest.get("leaves", [])
        if e.get("fingerprint")
    }
    if not entries:
        return
    try:
        installed = obj.state()
    except Exception as err:  # exotic wrappers without a state probe still restore
        rank_zero_debug(
            f"torchmetrics_tpu checkpoint: install verify skipped for {type(obj).__name__} ({err})"
        )
        return
    leaves, _ = _flatten_export(installed)
    for desc, arr in leaves:
        entry = entries.get((desc["leader"], desc["field"], desc["index"]))
        if entry is None:
            continue
        if list(arr.shape) != list(entry["shape"]) or str(arr.dtype) != entry["dtype"]:
            continue
        expected = [int(w) for w in entry["fingerprint"]]
        observed = [int(w) for w in _leaf_fingerprint(arr)]
        if observed != expected:
            field = entry.get("field")
            obs.counter_inc("checkpoint.integrity_mismatches")
            obs.fault_breadcrumb(
                "checkpoint_integrity_mismatch",
                domain="integrity",
                data={
                    "snapshot": os.path.basename(path),
                    "leader": entry.get("leader"),
                    "field": field,
                    "expected": expected,
                    "observed": observed,
                },
            )
            raise obs.flighted(
                StateDivergenceError(
                    f"{path}: installed state leaf {field!r} does not fingerprint-match the"
                    f" snapshot (expected {expected}, observed {observed}) — the restore"
                    " installed different bits than were saved",
                    surface="restore",
                    field=field,
                    expected=tuple(expected),
                    observed=tuple(observed),
                ),
                domain="integrity",
                snapshot=os.path.basename(path),
            )


def _restore_file(
    path: str, obj: Any, validate: str, check_finite: bool, topology: str = "strict"
) -> Dict[str, Any]:
    manifest, payload = _read_file(path)
    if validate != "off" and manifest.get("class") not in (None, type(obj).__name__):
        raise obs.flighted(StateCorruptionError(
            f"{path} holds state for {manifest.get('class')!r}, not {type(obj).__name__!r}"
            " (use validate='off' to force)"
        ), domain="checkpoint")
    action = _check_topology(path, manifest, obj, topology)
    target_capacity = getattr(obj, "capacity", None) if action == "remap" else None
    state = _decode_state(path, manifest, payload)
    # wrappers with their own state layouts override load_state without the
    # validate/check_finite kwargs (they validate structurally themselves) —
    # forward only what the target's signature accepts
    import inspect

    params = inspect.signature(obj.load_state).parameters
    kwargs: Dict[str, Any] = {}
    if "validate" in params:
        kwargs["validate"] = validate
    if "check_finite" in params:
        kwargs["check_finite"] = check_finite
    if target_capacity is not None and "target_capacity" in params:
        kwargs["target_capacity"] = target_capacity
    obj.load_state(state, **kwargs)
    if action in ("match", "legacy"):
        # verified recovery surface (integrity.py): re-fingerprint the state
        # the object actually INSTALLED against the manifest's pre-save
        # fingerprints — the per-leaf sha256 only covers bytes at rest, so a
        # flip introduced on the install path (H2D, aliasing, cast bug) would
        # otherwise restore silently. Elastic actions (fold/remap/reshard)
        # legitimately transform the bits and are structurally unverifiable.
        _verify_installed_state(path, manifest, obj)
    if action == "fold":
        # elastic: the stacked layout no longer matches this world — fold to
        # the topology-neutral canonical form NOW; the folded value is the
        # carried accumulation and the declared reductions make continued
        # updates exact (parallel/reshard.py)
        _force_fold(obj)
        obs.counter_inc("checkpoint.elastic_restores")
        rank_zero_debug(
            f"torchmetrics_tpu checkpoint: elastic restore folded {path}"
            f" ({(manifest.get('topology') or {}).get('num_shards')} shards ->"
            " topology-neutral canonical form)"
        )
    elif action in ("remap", "reshard"):
        obs.counter_inc("checkpoint.elastic_restores")
    manifest["topology_action"] = action
    return manifest


def restore_state(
    path: str,
    obj: Any,
    validate: str = "strict",
    check_finite: bool = False,
    on_fallback: Optional[Callable[[str, Exception], None]] = None,
    topology: str = "strict",
) -> Dict[str, Any]:
    """Restore ``obj``'s state from a snapshot file or rotating store.

    Single file: integrity checks (magic, manifest, payload + per-leaf
    sha256 — the torn-write detectors) raise
    :class:`CheckpointCorruptionError`; the decoded pytree then routes through
    ``obj.load_state(validate=..., check_finite=...)`` so disk restores get
    the full docs/ROBUSTNESS.md validation, including stacked sharded
    (deferred) layouts via the reserved shard-count key.

    ``topology`` decides what happens when the snapshot's saved world shape
    (the manifest's topology block) no longer matches this one — the
    preempted-and-rescheduled-onto-a-different-slice case
    (docs/DURABILITY.md "Elastic restore"):

    - ``"strict"`` (default): a stacked sharded snapshot whose shard count
      differs from this world's device count raises
      :class:`TopologyMismatchError` (in a rotating store it is *skipped*
      with a breadcrumb, like a torn file, and the next older snapshot is
      tried). Pre-topology-block (v1) snapshots restore with a logged
      warning, never an error.
    - ``"elastic"``: the stacked state is folded to its topology-neutral
      canonical form through the ``parallel/reshard.py`` seam and installed
      on THIS world — exact for all five reduction families; a laned
      snapshot is remapped into the instance's configured capacity
      (deterministic rehousing, evict-with-warning on shrink below
      occupancy).

    Rotating store (``path`` is a directory): snapshots are tried NEWEST
    first; a torn/corrupt/invalid/topology-mismatched snapshot is skipped
    (``on_fallback(path, error)`` observes each skip, default a rank-zero
    warning) and the next older one is tried — a damaged file is never
    silently installed. Raises :class:`CheckpointCorruptionError` when no
    snapshot is restorable.

    Returns the restored snapshot's manifest, with ``"path"``,
    ``"fallbacks_skipped"`` and ``"topology_action"`` attached.
    """
    if topology not in TOPOLOGY_POLICIES:
        raise ValueError(f"topology must be one of {TOPOLOGY_POLICIES}, got {topology!r}")
    with obs.span(obs.SPAN_CKPT_RESTORE, owner=type(obj).__name__):
        obs.counter_inc("checkpoint.restores")
        return _restore_state_body(path, obj, validate, check_finite, on_fallback, topology)


def _restore_state_body(
    path: str,
    obj: Any,
    validate: str,
    check_finite: bool,
    on_fallback: Optional[Callable[[str, Exception], None]],
    topology: str = "strict",
) -> Dict[str, Any]:
    if not os.path.isdir(path):
        manifest = _restore_file(path, obj, validate, check_finite, topology)
        manifest["path"] = path
        manifest["fallbacks_skipped"] = 0
        return manifest

    snaps = _list_snapshots(path)
    if not snaps:
        raise obs.flighted(CheckpointCorruptionError(f"no snapshots found in rotating store {path}"), domain="checkpoint")
    skipped = 0
    errors: List[str] = []
    for _, snap in reversed(snaps):
        try:
            manifest = _restore_file(snap, obj, validate, check_finite, topology)
        except (CheckpointCorruptionError, StateCorruptionError) as err:
            skipped += 1
            errors.append(f"{os.path.basename(snap)}: {type(err).__name__}: {err}")
            obs.counter_inc("checkpoint.restore_fallbacks")
            obs.fault_breadcrumb(
                "checkpoint_fallback",
                domain="checkpoint",
                data={"snapshot": os.path.basename(snap), "error": f"{type(err).__name__}: {err}"},
            )
            if on_fallback is not None:
                on_fallback(snap, err)
            else:
                rank_zero_warn(
                    f"torchmetrics_tpu checkpoint: skipping damaged snapshot {snap}"
                    f" ({type(err).__name__}: {err}); falling back to the previous one"
                )
            continue
        manifest["path"] = snap
        manifest["fallbacks_skipped"] = skipped
        return manifest
    raise obs.flighted(CheckpointCorruptionError(
        f"no valid snapshot in rotating store {path}; all {len(snaps)} damaged:\n  " + "\n  ".join(errors)
    ), domain="checkpoint")


# ------------------------------------------------------------------ autosave

class Autosaver:
    """Cadence-driven durable snapshots of a live metric/collection.

    Attach to any ``Metric`` or ``MetricCollection``; after every committed
    top-level ``update``/``forward`` the cadence is checked and, when due, a
    snapshot lands in the rotating store at ``directory``::

        saver = Autosaver(metric, "/ckpt/acc", every_n_updates=100).attach()
        ...  # training loop: saves trigger off committed updates
        saver.flush(); saver.detach()

    Cost model (the hot path must not feel the disk):

    - The host-side copy *reuses the executor's forced-copy recovery
      snapshot* when one is fresh enough (every donating call takes one
      anyway — ops/executor.py), so triggering a save usually costs zero
      extra device synchronisation. When no snapshot is reusable, a
      background save *rides the async read pipeline* (ops/async_read.py,
      ROADMAP): the hot path stages device REFERENCES (free — arrays are
      immutable and ``state()`` marks them escaped, double-buffering them
      against the next donating dispatch) and the D2H fetch runs on the
      pipeline worker instead of the step loop.
    - Serialization, hashing, and the fsync'd write run on a single
      background worker thread. If a save is still in flight when the next
      one triggers, the new one is SKIPPED (counted in ``stats`` — cadence
      too fast for the disk) rather than queued without bound.

    ``every_n_updates`` / ``every_s`` may be combined; whichever fires first
    wins and both clocks reset on a save. For loops that carry state outside
    the object (deferred epoch loops), call :meth:`step` with the external
    ``states`` pytree instead of attaching.
    """

    def __init__(
        self,
        obj: Any,
        directory: str,
        every_n_updates: Optional[int] = None,
        every_s: Optional[float] = None,
        keep: int = DEFAULT_KEEP,
        background: bool = True,
        reuse_recovery: bool = True,
    ) -> None:
        if every_n_updates is None and every_s is None:
            raise ValueError("Autosaver needs a cadence: every_n_updates and/or every_s")
        if every_n_updates is not None and every_n_updates < 1:
            raise ValueError(f"every_n_updates must be >= 1, got {every_n_updates}")
        if every_s is not None and every_s <= 0:
            raise ValueError(f"every_s must be > 0, got {every_s}")
        self.obj = obj
        self.directory = directory
        self.every_n_updates = every_n_updates
        self.every_s = every_s
        self.keep = keep
        self.background = background
        self.reuse_recovery = reuse_recovery
        self.stats: Dict[str, Any] = {
            "saves": 0,
            "skipped_inflight": 0,
            "reused_recovery_snapshots": 0,
            "async_rides": 0,
            "save_errors": 0,
            "last_path": None,
            "last_error": None,
            "last_save_unix": None,
        }
        self._updates_since_save = 0
        self._last_save_t = time.monotonic()
        # a background thread OR an async-read-pipeline future (the ride-along)
        self._inflight: Optional[Any] = None
        self._lock = threading.Lock()
        self._detach_fns: List[Callable[[], None]] = []

    def _inflight_alive(self) -> bool:
        inflight = self._inflight
        if inflight is None:
            return False
        if isinstance(inflight, threading.Thread):
            return inflight.is_alive()
        return not inflight.done()  # MetricFuture (ops/async_read.py)

    # ------------------------------------------------------------ observation
    def attach(self) -> "Autosaver":
        """Observe committed updates on the target (idempotent)."""
        if not self._detach_fns:
            self._detach_fns.append(self.obj.add_update_observer(self._on_update))
        return self

    def detach(self) -> None:
        for fn in self._detach_fns:
            fn()
        self._detach_fns.clear()

    def _on_update(self, _obj: Any) -> None:
        self._updates_since_save += 1
        self.maybe_save()

    def step(self, states: Optional[Dict[str, Any]] = None, sharded: bool = False) -> Optional[str]:
        """Manual cadence tick for loops not routed through update/forward
        (deferred epoch loops carrying external ``states``). Returns the path
        written when a save triggered, else None."""
        self._updates_since_save += 1
        return self.maybe_save(states=states, sharded=sharded)

    # ----------------------------------------------------------------- saving
    def _due(self) -> bool:
        if self.every_n_updates is not None and self._updates_since_save >= self.every_n_updates:
            return True
        if self.every_s is not None and (time.monotonic() - self._last_save_t) >= self.every_s:
            return True
        return False

    def maybe_save(self, states: Optional[Dict[str, Any]] = None, sharded: bool = False) -> Optional[str]:
        if not self._due():
            return None
        return self.save_now(states=states, sharded=sharded)

    def save_now(self, states: Optional[Dict[str, Any]] = None, sharded: bool = False) -> Optional[str]:
        """Trigger a save immediately: host copy on the calling thread, write
        on the worker (or inline when ``background=False``). Returns the
        (eventual) snapshot path, or None when skipped for an in-flight write."""
        with self._lock:
            if self._inflight_alive():
                self.stats["skipped_inflight"] += 1
                obs.counter_inc("autosave.skipped_inflight")
                return None
            # the autosave span covers exactly what the HOT PATH pays; with
            # the async-read ride-along (docs/ASYNC.md) a background save's
            # hot-path cost drops to staging device REFERENCES — the D2H copy
            # itself moves to the read-pipeline worker alongside the
            # serialization + fsync (which always ran off-thread)
            staged: Optional[Dict[str, Any]] = None
            ctx = None
            with obs.span(obs.SPAN_AUTOSAVE, owner=type(self.obj).__name__):
                obs.counter_inc("autosave.ticks")
                # captured INSIDE the tick span: the background write's
                # checkpoint.save span reopens this context, so the flow
                # arrow runs tick -> worker write across threads
                ctx = obs.capture_context()
                payload_states: Optional[Dict[str, Any]] = None
                if states is not None:
                    payload_states = host_copy_tree(states)
                else:
                    reusable = None
                    if self.reuse_recovery:
                        from torchmetrics_tpu.ops.executor import latest_recovery_snapshot

                        reusable = latest_recovery_snapshot(self.obj)
                    if reusable is not None:
                        _count, export = reusable  # already np copies, count keys embedded
                        self.stats["reused_recovery_snapshots"] += 1
                        payload_states = export
                    elif self.background:
                        # ROADMAP ride-along: no host copy on this thread at
                        # all — jax arrays are immutable, so staging
                        # references is free and state() marks them escaped
                        # (the executor's next donating dispatch copies first);
                        # the D2H runs on the read-pipeline worker
                        staged = self.obj.state()
                    else:
                        payload_states = host_copy_tree(self.obj.state())
                self._updates_since_save = 0
                self._last_save_t = time.monotonic()

            def write(export: Optional[Dict[str, Any]]) -> None:
                try:
                    written = save_state(
                        self.obj, self.directory, keep=self.keep, states=export, sharded=sharded
                    )
                    self.stats["saves"] += 1
                    self.stats["last_path"] = written
                    self.stats["last_save_unix"] = time.time()
                except Exception as err:
                    # an autosave failure must not kill the training step; it
                    # is recorded (and visible in stats) instead
                    self.stats["save_errors"] += 1
                    self.stats["last_error"] = f"{type(err).__name__}: {err}"
                    obs.counter_inc("autosave.save_errors")
                    obs.fault_breadcrumb(
                        "autosave_failed",
                        domain="autosave",
                        data={"error": f"{type(err).__name__}: {err}"},
                    )
                    rank_zero_warn(f"torchmetrics_tpu autosave failed: {type(err).__name__}: {err}")

            if staged is not None:
                from torchmetrics_tpu.ops.async_read import get_pipeline

                def ride() -> None:
                    with obs.use_context(ctx):
                        write(host_copy_tree(staged))

                self.stats["async_rides"] += 1
                obs.counter_inc("autosave.async_rides")
                self._inflight = get_pipeline().submit(
                    ride, owner=f"Autosaver({type(self.obj).__name__})"
                )
                return self.directory
            if not self.background:
                write(payload_states)
                return self.stats["last_path"]

            def bg_write() -> None:
                with obs.use_context(ctx):
                    write(payload_states)

            worker = threading.Thread(target=bg_write, name="tm_tpu_autosave", daemon=True)
            self._inflight = worker
            worker.start()
        # background mode: the concrete snapshot path lands in stats["last_path"]
        # once the worker commits; the store directory is the stable address
        return self.directory

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until any in-flight background write completes (a dedicated
        writer thread or a read-pipeline ride-along future alike)."""
        worker = self._inflight
        if worker is None:
            return
        if isinstance(worker, threading.Thread):
            if worker.is_alive():
                worker.join(timeout)
        else:
            worker.wait(timeout)  # MetricFuture: resolves when the write landed

    def final_save(self) -> Optional[str]:
        """Synchronous last-gasp snapshot (the preemption-handler path): waits
        for any in-flight write, then saves the CURRENT live state inline —
        no recovery-snapshot reuse, no background thread."""
        self.flush()
        reuse, background = self.reuse_recovery, self.background
        self.reuse_recovery = False
        self.background = False
        try:
            return self.save_now()
        finally:
            self.reuse_recovery, self.background = reuse, background


# -------------------------------------------------------------- preemption

class PreemptionHandle:
    """Installed signal hooks; ``uninstall()`` restores the previous handlers."""

    def __init__(self, saver: Autosaver, signums: Tuple[int, ...]) -> None:
        import signal as _signal

        self._saver = saver
        self._previous: Dict[int, Any] = {}
        self.flushes = 0
        for signum in signums:
            self._previous[signum] = _signal.getsignal(signum)
            _signal.signal(signum, self._handle)

    def _handle(self, signum: int, frame: Any) -> None:
        import signal as _signal

        self.flushes += 1
        try:
            self._saver.final_save()
        except Exception as err:  # the chained handler must still run on a failed flush
            rank_zero_warn(f"torchmetrics_tpu preemption flush failed: {type(err).__name__}: {err}")
        previous = self._previous.get(signum)
        if callable(previous):
            previous(signum, frame)
        elif signum == _signal.SIGINT:
            raise KeyboardInterrupt
        elif previous is _signal.SIG_DFL:
            # re-deliver with the default disposition so exit codes stay honest
            _signal.signal(signum, _signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def uninstall(self) -> None:
        import signal as _signal

        for signum, previous in self._previous.items():
            _signal.signal(signum, previous)
        self._previous.clear()


def install_preemption_handler(
    saver: Autosaver, signums: Optional[Tuple[int, ...]] = None
) -> PreemptionHandle:
    """Flush one final snapshot when the process is told to die.

    Registers handlers for SIGTERM and SIGINT (override via ``signums``) that
    run ``saver.final_save()`` — synchronous, current live state — then chain
    to the previously-installed handler (or re-deliver the default
    disposition), so a preempted pod loses at most the batches since the last
    committed update, not the epoch. Must be called from the main thread
    (CPython restriction on ``signal.signal``); returns a handle whose
    ``uninstall()`` restores the previous handlers.
    """
    import signal as _signal

    if signums is None:
        signums = (_signal.SIGTERM, _signal.SIGINT)
    return PreemptionHandle(saver, tuple(signums))
