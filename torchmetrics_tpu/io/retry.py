"""Transient-failure policy: capped exponential backoff and stall watchdog.

TPU fleets fail in two time signatures. *Transient* failures (a DCN collective
aborted by a peer restart, a runtime dispatch rejected during a driver hiccup)
succeed on a re-attempt seconds later — the right response is capped
exponential backoff with jitter, not an epoch-losing crash. *Stalls* (a
rendezvous whose peer died, a wedged donating dispatch) never return at all —
the right response is a deadline that converts the silent hang into a typed
:class:`~torchmetrics_tpu.utils.exceptions.DispatchStallError` the caller can
checkpoint-and-exit on (docs/DURABILITY.md).

This module provides both primitives and the env-var plumbing that wires them
into the two seams that need them:

- ``Metric(on_sync_failure="retry")`` / ``TORCHMETRICS_TPU_SYNC_RETRIES`` —
  the multi-host ``process_allgather`` path (``parallel/sync.py``).
- ``TORCHMETRICS_TPU_DISPATCH_RETRIES`` — the executor's warm-dispatch
  recovery path (``ops/executor.py``): state is restored from the host-side
  recovery snapshot, then the dispatch re-runs on a fresh copy.
- ``TORCHMETRICS_TPU_DISPATCH_DEADLINE`` — seconds before a donating compiled
  call is declared stalled (off when unset).
"""
from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Generator, Iterator, Optional, Tuple, Type, Union

from torchmetrics_tpu.utils.exceptions import DispatchStallError
from torchmetrics_tpu.utils.prints import rank_zero_debug

#: env var: how many times a failed multi-host sync re-attempts under
#: ``on_sync_failure="retry"`` (int >= 0; default 3 when the policy is chosen
#: without an explicit count)
SYNC_RETRIES_ENV = "TORCHMETRICS_TPU_SYNC_RETRIES"

#: env var: how many times a failed WARM executor dispatch re-attempts (on a
#: fresh state copy, after the recovery restore) before propagating; 0
#: (default) keeps the restore-and-raise semantics of docs/EXECUTOR.md
DISPATCH_RETRIES_ENV = "TORCHMETRICS_TPU_DISPATCH_RETRIES"

#: env var: seconds before a donating compiled dispatch is declared stalled
#: (DispatchStallError); unset/0 disables the watchdog
DISPATCH_DEADLINE_ENV = "TORCHMETRICS_TPU_DISPATCH_DEADLINE"

#: default sync retry count when ``on_sync_failure="retry"`` is selected but
#: the env var is unset
DEFAULT_SYNC_RETRIES = 3


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer retry count, got {raw!r}")
    if value < 0:
        raise ValueError(f"{name} must be >= 0, got {value}")
    return value


def default_sync_retries() -> int:
    """Retry count for ``on_sync_failure="retry"`` (``TORCHMETRICS_TPU_SYNC_RETRIES``)."""
    return _env_int(SYNC_RETRIES_ENV, DEFAULT_SYNC_RETRIES)


def default_dispatch_retries() -> int:
    """Warm-dispatch retry count (``TORCHMETRICS_TPU_DISPATCH_RETRIES``, default 0)."""
    return _env_int(DISPATCH_RETRIES_ENV, 0)


def default_dispatch_deadline() -> Optional[float]:
    """Watchdog deadline in seconds (``TORCHMETRICS_TPU_DISPATCH_DEADLINE``), or None."""
    raw = os.environ.get(DISPATCH_DEADLINE_ENV, "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(f"{DISPATCH_DEADLINE_ENV} must be a number of seconds, got {raw!r}")
    return value if value > 0 else None


@dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with jitter.

    ``delay(k) = min(max_delay, base_delay * multiplier**k) * (1 + U(-jitter, jitter))``
    for attempt k in [0, max_retries). ``jitter=0`` makes the schedule exactly
    deterministic (tests); the default de-synchronises a fleet retrying the
    same dead rendezvous so the recovered peer is not hit by a thundering herd.
    """

    max_retries: int = 3
    base_delay: float = 0.05
    max_delay: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.25

    def __post_init__(self) -> None:
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")
        if not 0 <= self.jitter < 1:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")


def backoff_delays(policy: RetryPolicy, seed: Optional[int] = None) -> Iterator[float]:
    """The policy's delay schedule, one value per retry attempt.

    >>> [round(d, 3) for d in backoff_delays(RetryPolicy(max_retries=4, jitter=0.0))]
    [0.05, 0.1, 0.2, 0.4]
    """
    import random

    rng = random.Random(seed)
    for k in range(policy.max_retries):
        delay = min(policy.max_delay, policy.base_delay * policy.multiplier**k)
        if policy.jitter:
            delay *= 1.0 + rng.uniform(-policy.jitter, policy.jitter)
        yield delay


def call_with_retries(
    fn: Callable[[], Any],
    policy: RetryPolicy,
    retry_on: Union[Type[BaseException], Tuple[Type[BaseException], ...]] = Exception,
    no_retry_on: Tuple[Type[BaseException], ...] = (DispatchStallError,),
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    what: str = "call",
) -> Any:
    """Run ``fn`` with up to ``policy.max_retries`` backed-off re-attempts.

    ``no_retry_on`` exceptions propagate immediately even when they match
    ``retry_on`` — a :class:`DispatchStallError` by default: re-running a call
    that just hung for its whole deadline would park the loop for another one.
    ``on_retry(attempt, error, delay)`` fires before each sleep (observability
    seam; the executor counts these into its stats).
    """
    delays = backoff_delays(policy)
    attempt = 0
    while True:
        try:
            return fn()
        except no_retry_on:
            raise
        except retry_on as err:
            delay = next(delays, None)
            if delay is None:
                raise  # budget exhausted: propagate the final failure
            attempt += 1
            from torchmetrics_tpu import obs  # deferred: io.retry loads before obs in some paths

            obs.counter_inc("retry.attempts")
            if on_retry is not None:
                on_retry(attempt, err, delay)
            else:
                rank_zero_debug(
                    f"torchmetrics_tpu retry: {what} failed ({type(err).__name__}: {err});"
                    f" attempt {attempt}/{policy.max_retries} in {delay:.3f}s"
                )
            sleep(delay)


# --------------------------------------------------------------------- watchdog

@contextmanager
def stall_watchdog(
    deadline: Optional[float],
    what: str = "compiled dispatch",
    status: Optional[Callable[[], Any]] = None,
) -> Generator[None, None, None]:
    """Bound a blocking call: raise :class:`DispatchStallError` at ``deadline``
    seconds instead of hanging the loop forever.

    A wedged donating dispatch (or a rendezvous whose peer died) blocks inside
    the runtime where no Python timeout can reach, so the watchdog thread
    delivers a real SIGINT to the main thread (``signal.pthread_kill`` — an OS
    signal actually wakes a blocked syscall, unlike ``interrupt_main``'s
    flag-only path, which is the fallback) and the context manager converts
    the resulting ``KeyboardInterrupt`` into the typed error, attaching
    ``status()`` breadcrumbs (e.g. ``executor_status``) so the operator sees
    *which* call wedged and in what state. A custom SIGINT handler installed
    by the application (including :func:`install_preemption_handler`) runs
    first — a preemption flush before the stall error is the intended
    interplay.

    Only the MAIN thread can receive the interrupt: on any other thread the
    watchdog is a no-op (logged once at debug level). ``deadline`` None/<=0
    disables the guard entirely. The stalled call itself cannot be cancelled —
    treat a stall as this process's cue to checkpoint local state and exit
    (docs/DURABILITY.md), not to retry.
    """
    if deadline is None or deadline <= 0:
        yield
        return
    if threading.current_thread() is not threading.main_thread():
        rank_zero_debug(
            f"torchmetrics_tpu stall_watchdog: not on the main thread; cannot deliver"
            f" the interrupt — {what} runs unguarded"
        )
        yield
        return
    main_ident = threading.main_thread().ident
    done = threading.Event()
    fired = threading.Event()

    def deliver() -> None:
        import signal as _signal

        try:
            # a real OS signal: wakes the main thread even inside a blocked
            # syscall (time.sleep, lock waits, runtime rendezvous polls)
            _signal.pthread_kill(main_ident, _signal.SIGINT)
            return
        except (AttributeError, ProcessLookupError, OSError):
            pass
        import _thread

        _thread.interrupt_main()  # flag-only fallback: fires at the next bytecode

    def watch() -> None:
        if not done.wait(deadline) and not done.is_set():
            fired.set()
            deliver()

    watcher = threading.Thread(target=watch, name="tm_tpu_watchdog", daemon=True)
    watcher.start()
    try:
        yield
    except KeyboardInterrupt:
        done.set()
        if fired.is_set():
            breadcrumbs = None
            if status is not None:
                try:
                    breadcrumbs = status()
                except Exception as err:  # breadcrumbs must never mask the stall itself
                    rank_zero_debug(f"torchmetrics_tpu stall_watchdog: status() failed ({err})")
                    breadcrumbs = None
            # route the stall through the diagnostic trail (obs/registry.py):
            # dump_diagnostics() after the crash shows WHAT stalled and the
            # executor's counters at that moment, not just the final traceback.
            # A stall is FATAL by contract (checkpoint and exit), so the
            # flight recorder also persists to disk — the post-mortem black
            # box survives the process (docs/OBSERVABILITY.md).
            from torchmetrics_tpu import obs  # deferred: io.retry loads before obs in some paths

            obs.counter_inc("watchdog.stalls")
            raise obs.flighted(
                DispatchStallError(
                    f"{what} did not complete within {deadline}s (stalled runtime call;"
                    " checkpoint local state and restart this process)"
                    + (f"; executor_status={breadcrumbs}" if breadcrumbs is not None else ""),
                    executor_status=breadcrumbs,
                ),
                domain="dispatch",
                kind="dispatch_stall",
                persist=True,
                what=what,
                deadline_s=deadline,
                executor_status=breadcrumbs,
            ) from None
        raise
    else:
        done.set()
        if fired.is_set():
            # the call returned inside the race window after the watchdog fired:
            # absorb the in-flight interrupt so it cannot detonate at an
            # arbitrary later bytecode boundary
            t_end = time.monotonic() + 0.2
            try:
                while time.monotonic() < t_end:
                    time.sleep(0.005)
                rank_zero_debug(
                    f"torchmetrics_tpu stall_watchdog: {what} completed at the deadline;"
                    " pending interrupt not observed within the absorption window"
                )
            except KeyboardInterrupt:
                pass
    finally:
        done.set()
