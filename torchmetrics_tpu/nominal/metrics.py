"""Modular nominal metrics (reference nominal/*.py): a (C, C) confusion-matrix
sum state per metric; FleissKappa concatenates per-batch counts."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.nominal.metrics import (
    _cramers_v_compute,
    _fleiss_kappa_compute,
    _fleiss_kappa_update,
    _nominal_confmat_update,
    _nominal_input_validation,
    _pearsons_contingency_coefficient_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class _ConfmatNominalMetric(Metric):
    """Shared state machinery for the chi-square-on-confmat family."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        nan_strategy: str = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_classes, int) and num_classes > 0):
            raise ValueError(f"Argument `num_classes` is expected to be a positive integer, but got {num_classes}")
        self.num_classes = num_classes
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes)), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        confmat = _nominal_confmat_update(
            preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value
        )
        self.confmat = self.confmat + confmat


class CramersV(_ConfmatNominalMetric):
    """Cramers V (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.nominal import CramersV
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> m = CramersV(num_classes=3)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.6667
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        return _cramers_v_compute(self.confmat, self.bias_correction)


class TschuprowsT(_ConfmatNominalMetric):
    """Tschuprows T (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.nominal import TschuprowsT
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> m = TschuprowsT(num_classes=3)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.6667
    """

    def __init__(self, num_classes: int, bias_correction: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, **kwargs)
        self.bias_correction = bias_correction

    def compute(self) -> Array:
        return _tschuprows_t_compute(self.confmat, self.bias_correction)


class PearsonsContingencyCoefficient(_ConfmatNominalMetric):
    """Pearsons Contingency Coefficient (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.nominal import PearsonsContingencyCoefficient
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> m = PearsonsContingencyCoefficient(num_classes=3)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.7559
    """

    def compute(self) -> Array:
        return _pearsons_contingency_coefficient_compute(self.confmat)


class TheilsU(_ConfmatNominalMetric):
    """Theils U (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.nominal import TheilsU
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0])
        >>> m = TheilsU(num_classes=3)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.7103
    """

    def compute(self) -> Array:
        return _theils_u_compute(self.confmat)


class FleissKappa(Metric):
    """Fleiss Kappa (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.nominal import FleissKappa
        >>> import jax.numpy as jnp
        >>> ratings = jnp.asarray([[2, 1, 0], [1, 2, 0], [0, 1, 2], [3, 0, 0]])
        >>> m = FleissKappa()
        >>> m.update(ratings)
        >>> round(float(m.compute()), 4)
        0.1818
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, mode: str = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ["counts", "probs"]:
            raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
        self.mode = mode
        self.add_state("counts", default=[], dist_reduce_fx="cat")

    def update(self, ratings: Array) -> None:
        counts = _fleiss_kappa_update(ratings, self.mode)
        self.counts.append(counts)

    def compute(self) -> Array:
        return _fleiss_kappa_compute(dim_zero_cat(self.counts))
