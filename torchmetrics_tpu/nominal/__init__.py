from torchmetrics_tpu.nominal.metrics import (  # noqa: F401
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = ["CramersV", "FleissKappa", "PearsonsContingencyCoefficient", "TheilsU", "TschuprowsT"]
