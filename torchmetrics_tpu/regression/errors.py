"""Modular error metrics.

Reference: regression/{mae,mse,log_mse,mape,symmetric_mape,wmape,rse,log_cosh,
minkowski,tweedie_deviance,csi}.py — sum+count tensor states, psum-synced.
"""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.basic import (
    _critical_success_index_update,
    _log_cosh_error_update,
    _mean_absolute_error_update,
    _mean_absolute_percentage_error_update,
    _mean_squared_error_update,
    _mean_squared_log_error_update,
    _minkowski_distance_update,
    _relative_squared_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
    _tweedie_deviance_score_update,
    _weighted_mean_absolute_percentage_error_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.compute import _at_least_float32, _safe_divide


class MeanAbsoluteError(Metric):
    """Mean Absolute Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import MeanAbsoluteError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = MeanAbsoluteError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.5
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_abs_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, num_obs = _mean_absolute_error_update(jnp.asarray(preds), jnp.asarray(target))
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return self.sum_abs_error / self.total


class MeanSquaredError(Metric):
    """Mean Squared Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = MeanSquaredError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.375
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_squared_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, num_obs = _mean_squared_error_update(
            jnp.asarray(preds), jnp.asarray(target), self.num_outputs
        )
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        mse = self.sum_squared_error / self.total
        return mse if self.squared else jnp.sqrt(mse)


class MeanSquaredLogError(Metric):
    """Mean Squared Log Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import MeanSquaredLogError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = MeanSquaredLogError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.128
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, n = _mean_squared_log_error_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.sum_squared_log_error = self.sum_squared_log_error + s
        self.total = self.total + n

    def compute(self) -> Array:
        return self.sum_squared_log_error / self.total


class MeanAbsolutePercentageError(Metric):
    """Mean Absolute Percentage Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import MeanAbsolutePercentageError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = MeanAbsolutePercentageError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.3274
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, n = _mean_absolute_percentage_error_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.sum_abs_per_error = self.sum_abs_per_error + s
        self.total = self.total + n

    def compute(self) -> Array:
        return self.sum_abs_per_error / self.total


class SymmetricMeanAbsolutePercentageError(MeanAbsolutePercentageError):
    """Symmetric Mean Absolute Percentage Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import SymmetricMeanAbsolutePercentageError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = SymmetricMeanAbsolutePercentageError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.5788
    """

    plot_upper_bound: float = 2.0

    def update(self, preds: Array, target: Array) -> None:
        s, n = _symmetric_mean_absolute_percentage_error_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.sum_abs_per_error = self.sum_abs_per_error + s
        self.total = self.total + n


class WeightedMeanAbsolutePercentageError(Metric):
    """Weighted Mean Absolute Percentage Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import WeightedMeanAbsolutePercentageError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = WeightedMeanAbsolutePercentageError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.16
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_scale", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, t = _weighted_mean_absolute_percentage_error_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.sum_abs_error = self.sum_abs_error + s
        self.sum_scale = self.sum_scale + t

    def compute(self) -> Array:
        return self.sum_abs_error / jnp.clip(self.sum_scale, min=1.17e-06)


class RelativeSquaredError(Metric):
    """Relative Squared Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import RelativeSquaredError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = RelativeSquaredError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.0514
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.squared = squared
        self.add_state("sum_squared_obs", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("sum_obs", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        # sums of squares overflow f16 (max ~65k) before reaching the f32 state
        preds = _at_least_float32(preds)
        target = _at_least_float32(target)
        self.sum_squared_obs = self.sum_squared_obs + (target * target).sum(0)
        self.sum_obs = self.sum_obs + target.sum(0)
        self.sum_squared_error = self.sum_squared_error + ((target - preds) ** 2).sum(0)
        self.total = self.total + target.shape[0]

    def compute(self) -> Array:
        return _relative_squared_error_compute(
            self.sum_squared_obs, self.sum_obs, self.sum_squared_error, self.total, self.squared
        )


class LogCoshError(Metric):
    """Log Cosh Error (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import LogCoshError
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = LogCoshError()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.1685
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_log_cosh_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, n = _log_cosh_error_update(
            jnp.asarray(preds), jnp.asarray(target), self.num_outputs
        )
        self.sum_log_cosh_error = self.sum_log_cosh_error + s
        self.total = self.total + n

    def compute(self) -> Array:
        return (self.sum_log_cosh_error / self.total).squeeze()


class MinkowskiDistance(Metric):
    """Minkowski Distance (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import MinkowskiDistance
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = MinkowskiDistance(p=3)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        1.0772
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, p: float, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, (float, int)) and p >= 1):
            raise ValueError(f"Argument ``p`` expected to be a float larger than 1, but got {p}")
        self.p = p
        self.add_state("minkowski_dist_sum", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        self.minkowski_dist_sum = self.minkowski_dist_sum + _minkowski_distance_update(
            jnp.asarray(preds), jnp.asarray(target), self.p
        )

    def compute(self) -> Array:
        return self.minkowski_dist_sum ** (1.0 / self.p)


class TweedieDevianceScore(Metric):
    """Tweedie Deviance Score (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import TweedieDevianceScore
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = TweedieDevianceScore()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.375
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_observations", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        s, n = _tweedie_deviance_score_update(
            jnp.asarray(preds), jnp.asarray(target), self.power
        )
        self.sum_deviance_score = self.sum_deviance_score + s
        self.num_observations = self.num_observations + n

    def compute(self) -> Array:
        return self.sum_deviance_score / self.num_observations


class CriticalSuccessIndex(Metric):
    """Critical Success Index (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.regression import CriticalSuccessIndex
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = CriticalSuccessIndex(threshold=0.5)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, threshold: float, keep_sequence_dim: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(threshold, (int, float)):
            raise ValueError(f"Expected argument `threshold` to be a float but got {threshold}")
        self.threshold = float(threshold)
        if keep_sequence_dim is not None and (not isinstance(keep_sequence_dim, int) or keep_sequence_dim < 0):
            raise ValueError(f"Expected argument `keep_sequence_dim` to be an int but got {keep_sequence_dim}")
        self.keep_sequence_dim = keep_sequence_dim
        if keep_sequence_dim is None:
            self.add_state("hits", jnp.asarray(0), dist_reduce_fx="sum")
            self.add_state("misses", jnp.asarray(0), dist_reduce_fx="sum")
            self.add_state("false_alarms", jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("hits", [], dist_reduce_fx="cat")
            self.add_state("misses", [], dist_reduce_fx="cat")
            self.add_state("false_alarms", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        hits, misses, false_alarms = _critical_success_index_update(
            jnp.asarray(preds), jnp.asarray(target), self.threshold, self.keep_sequence_dim
        )
        if self.keep_sequence_dim is None:
            self.hits = self.hits + hits
            self.misses = self.misses + misses
            self.false_alarms = self.false_alarms + false_alarms
        else:
            self.hits.append(hits)
            self.misses.append(misses)
            self.false_alarms.append(false_alarms)

    def compute(self) -> Array:
        from torchmetrics_tpu.utils.data import dim_zero_cat

        if self.keep_sequence_dim is None:
            hits, misses, fa = self.hits, self.misses, self.false_alarms
        else:
            hits, misses, fa = dim_zero_cat(self.hits), dim_zero_cat(self.misses), dim_zero_cat(self.false_alarms)
        return _safe_divide(hits, hits + misses + fa)
