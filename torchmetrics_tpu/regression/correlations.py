"""Modular correlation / variance-explained metrics.

Reference: regression/{pearson,spearman,kendall,concordance,r2,explained_variance}.py.
PearsonCorrCoef carries mean/var/cov moment states with ``dist_reduce_fx=None``
(raw per-rank stack) merged by the Chan pairwise formula in compute — the
reference's template for all TPU moment-merging (regression/pearson.py:28-70).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.explained_variance import (
    ALLOWED_MULTIOUTPUT,
    _explained_variance_compute,
    _explained_variance_update,
)
from torchmetrics_tpu.functional.regression.pearson import (
    _final_aggregation,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from torchmetrics_tpu.functional.regression.r2 import _r2_score_compute, _r2_score_update
from torchmetrics_tpu.functional.regression.rank_based import (
    _concordance_corrcoef_compute,
    _spearman_corrcoef_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class PearsonCorrCoef(Metric):
    """Pearson correlation (reference regression/pearson.py:73).

    Example:
        >>> from torchmetrics_tpu.regression import PearsonCorrCoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = PearsonCorrCoef()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.9849
    """

    is_differentiable = True
    higher_is_better = None
    full_state_update = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("mean_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("mean_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("corr_xy", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("n_total", jnp.zeros(num_outputs), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds, dtype=jnp.float32)
        target = jnp.asarray(target, dtype=jnp.float32)
        if self.num_outputs == 1 and preds.ndim == 1:
            preds = preds[:, None]
            target = target[:, None]
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds,
            target,
            self.mean_x,
            self.mean_y,
            self.var_x,
            self.var_y,
            self.corr_xy,
            self.n_total,
            self.num_outputs,
        )

    def compute(self) -> Array:
        if self.mean_x.ndim > 1:  # synced: stacked per-rank states → Chan merge
            _, _, var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)


class SpearmanCorrCoef(Metric):
    """Spearman correlation (reference regression/spearman.py): rank + Pearson.

    Example:
        >>> from torchmetrics_tpu.regression import SpearmanCorrCoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = SpearmanCorrCoef()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(jnp.asarray(preds, dtype=jnp.float32))
        self.target.append(jnp.asarray(target, dtype=jnp.float32))

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)


class KendallRankCorrCoef(Metric):
    """Kendall tau (reference regression/kendall.py): list states, O(n²) kernel.

    Example:
        >>> from torchmetrics_tpu.regression import KendallRankCorrCoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = KendallRankCorrCoef()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        1.0
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        variant: str = "b",
        t_test: bool = False,
        alternative: Optional[str] = "two-sided",
        num_outputs: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if variant not in ("a", "b", "c"):
            raise ValueError(f"Argument `variant` is expected to be one of 'a', 'b', 'c' but got {variant}")
        if not isinstance(t_test, bool):
            raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test}.")
        if t_test and alternative not in ("two-sided", "less", "greater"):
            raise ValueError("Argument `alternative` is expected to be one of 'two-sided', 'less', 'greater'")
        self.variant = variant
        self.t_test = t_test
        self.alternative = alternative
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(jnp.asarray(preds, dtype=jnp.float32))
        self.target.append(jnp.asarray(target, dtype=jnp.float32))

    def compute(self):
        from torchmetrics_tpu.functional.regression.rank_based import kendall_rank_corrcoef

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return kendall_rank_corrcoef(preds, target, self.variant, self.t_test, self.alternative)


class ConcordanceCorrCoef(Metric):
    """Lin's concordance correlation (reference regression/concordance.py).

    Example:
        >>> from torchmetrics_tpu.regression import ConcordanceCorrCoef
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = ConcordanceCorrCoef()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.9777
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("mean_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("mean_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_x", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("var_y", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("corr_xy", jnp.zeros(num_outputs), dist_reduce_fx=None)
        self.add_state("n_total", jnp.zeros(num_outputs), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds, dtype=jnp.float32)
        target = jnp.asarray(target, dtype=jnp.float32)
        if self.num_outputs == 1 and preds.ndim == 1:
            preds = preds[:, None]
            target = target[:, None]
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds,
            target,
            self.mean_x,
            self.mean_y,
            self.var_x,
            self.var_y,
            self.corr_xy,
            self.n_total,
            self.num_outputs,
        )

    def compute(self) -> Array:
        if self.mean_x.ndim > 1:
            mean_x, mean_y, var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            mean_x, mean_y, var_x, var_y, corr_xy, n_total = (
                self.mean_x,
                self.mean_y,
                self.var_x,
                self.var_y,
                self.corr_xy,
                self.n_total,
            )
        # reference shape semantics: (num_outputs,) even for single output
        return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, n_total)


class R2Score(Metric):
    """R² (reference regression/r2.py).

    Example:
        >>> from torchmetrics_tpu.regression import R2Score
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = R2Score()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.9486
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, num_outputs: int = 1, adjusted: int = 0, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}")
        self.multioutput = multioutput
        self.add_state("sum_squared_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("sum_error", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("residual", jnp.zeros(num_outputs).squeeze(), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, residual, num_obs = _r2_score_update(jnp.asarray(preds), jnp.asarray(target))
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + residual
        self.total = self.total + num_obs

    def compute(self) -> Array:
        # concretize the count when possible so the n<2 and adjusted-r2
        # guards in _r2_score_compute apply to the class path too (they are
        # host-side checks; a traced count under jit skips them)
        total = self.total
        try:
            total = int(total)
        except (TypeError, jax.errors.TracerIntegerConversionError):
            pass
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, total, self.adjusted, self.multioutput
        )


class ExplainedVariance(Metric):
    """Explained variance (reference regression/explained_variance.py).

    Example:
        >>> from torchmetrics_tpu.regression import ExplainedVariance
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> m = ExplainedVariance()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.9572
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if multioutput not in ALLOWED_MULTIOUTPUT:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {ALLOWED_MULTIOUTPUT}")
        self.multioutput = multioutput
        self.add_state("sum_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_obs", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        num_obs, sum_error, ss_error, sum_target, ss_target = _explained_variance_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.num_obs = self.num_obs + num_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + ss_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + ss_target

    def compute(self) -> Array:
        return _explained_variance_compute(
            self.num_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )
