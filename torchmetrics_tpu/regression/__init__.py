from torchmetrics_tpu.regression.correlations import (  # noqa: F401
    ConcordanceCorrCoef,
    ExplainedVariance,
    KendallRankCorrCoef,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
)
from torchmetrics_tpu.regression.errors import (  # noqa: F401
    CriticalSuccessIndex,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    RelativeSquaredError,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from torchmetrics_tpu.regression.misc import CosineSimilarity, KLDivergence  # noqa: F401

__all__ = [
    "ConcordanceCorrCoef",
    "CosineSimilarity",
    "CriticalSuccessIndex",
    "ExplainedVariance",
    "KLDivergence",
    "KendallRankCorrCoef",
    "LogCoshError",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "MinkowskiDistance",
    "PearsonCorrCoef",
    "R2Score",
    "RelativeSquaredError",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
