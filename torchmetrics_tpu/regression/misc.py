"""Modular CosineSimilarity and KLDivergence (reference regression/{cosine_similarity,kl_divergence}.py)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.regression.misc import _cosine_similarity_compute, _kld_compute, _kld_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class CosineSimilarity(Metric):
    """Cosine similarity with list states (reference regression/cosine_similarity.py).

    Example:
        >>> from torchmetrics_tpu.regression import CosineSimilarity
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0], [0.0, 1.0, 0.5]])
        >>> target = jnp.asarray([[1.0, 2.0, 2.5], [0.0, 1.0, 1.0]])
        >>> m = CosineSimilarity()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        1.9447
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        self.preds.append(jnp.asarray(preds, dtype=jnp.float32))
        self.target.append(jnp.asarray(target, dtype=jnp.float32))

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)


class KLDivergence(Metric):
    """KL divergence (reference regression/kl_divergence.py).

    Example:
        >>> from torchmetrics_tpu.regression import KLDivergence
        >>> import jax.numpy as jnp
        >>> p = jnp.asarray([[0.3, 0.3, 0.4]])
        >>> q = jnp.asarray([[0.25, 0.5, 0.25]])
        >>> m = KLDivergence()
        >>> m.update(p, q)
        >>> round(float(m.compute()), 4)
        0.0895
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, log_prob: bool = False, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument to be a bool but got {log_prob}")
        self.log_prob = log_prob
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        if self.reduction in ("mean", "sum"):
            self.add_state("measures", jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("measures", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array) -> None:
        measures, total = _kld_update(jnp.asarray(p, dtype=jnp.float32), jnp.asarray(q, dtype=jnp.float32), self.log_prob)
        if self.reduction in ("none", None):
            self.measures.append(measures)
        else:
            self.measures = self.measures + measures.sum()
        self.total = self.total + total

    def compute(self) -> Array:
        measures = dim_zero_cat(self.measures) if self.reduction in ("none", None) else self.measures
        return _kld_compute(measures, self.total, self.reduction)
