"""Modular SNR metrics (reference audio/snr.py:35-314): mean over all samples seen."""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from torchmetrics_tpu.metric import Metric


class SignalNoiseRatio(Metric):
    """Signal Noise Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = SignalNoiseRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        20.0
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        return self.sum_snr / self.total


class ScaleInvariantSignalNoiseRatio(Metric):
    """Scale Invariant Signal Noise Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalNoiseRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = ScaleInvariantSignalNoiseRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        20.0
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -20.0
    plot_upper_bound: float = 10.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        return self.sum_si_snr / self.total


class ComplexScaleInvariantSignalNoiseRatio(Metric):
    """Complex Scale Invariant Signal Noise Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import ComplexScaleInvariantSignalNoiseRatio
        >>> import jax.numpy as jnp
        >>> target = jnp.stack([jnp.cos(jnp.arange(20.0)).reshape(4, 5), jnp.sin(jnp.arange(20.0)).reshape(4, 5)], axis=-1)
        >>> preds = target * 0.9 + 0.01
        >>> m = ComplexScaleInvariantSignalNoiseRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        36.0883
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be an bool, but got {zero_mean}")
        self.zero_mean = zero_mean
        self.add_state("ci_snr_sum", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        v = complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.ci_snr_sum = self.ci_snr_sum + jnp.sum(v)
        self.num = self.num + v.size

    def compute(self) -> Array:
        return self.ci_snr_sum / self.num
