"""Modular SNR metrics (reference audio/snr.py:35-314): mean over all samples seen."""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from torchmetrics_tpu.metric import Metric


class SignalNoiseRatio(Metric):
    full_state_update = False
    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        snr_batch = signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        return self.sum_snr / self.total


class ScaleInvariantSignalNoiseRatio(Metric):
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -20.0
    plot_upper_bound: float = 10.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_si_snr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_snr_batch = scale_invariant_signal_noise_ratio(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        return self.sum_si_snr / self.total


class ComplexScaleInvariantSignalNoiseRatio(Metric):
    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be an bool, but got {zero_mean}")
        self.zero_mean = zero_mean
        self.add_state("ci_snr_sum", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        v = complex_scale_invariant_signal_noise_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.ci_snr_sum = self.ci_snr_sum + jnp.sum(v)
        self.num = self.num + v.size

    def compute(self) -> Array:
        return self.ci_snr_sum / self.num
