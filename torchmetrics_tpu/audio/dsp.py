"""Modular DSP-backed speech metrics: PESQ, STOI, SRMR.

Reference classes: audio/pesq.py:29-173, audio/stoi.py:30-160,
audio/srmr.py:33-187 — all three accumulate a running score sum + count
(dist_reduce_fx="sum") over per-signal scores computed by the functional
layer; the DSP itself is first-party here (C++ PESQ kernel, numpy STOI/SRMR)
instead of the reference's external wheels.
"""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from torchmetrics_tpu.functional.audio.srmr import (
    _srmr_arg_validate,
    speech_reverberation_modulation_energy_ratio,
)
from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility
from torchmetrics_tpu.metric import Metric


class PerceptualEvaluationSpeechQuality(Metric):
    """PESQ MOS-LQO averaged over all signals seen (reference audio/pesq.py:29-173).

    Example:
        >>> from torchmetrics_tpu.audio import PerceptualEvaluationSpeechQuality
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 8000.0)
        >>> target = jnp.sin(2 * jnp.pi * 440 * t)
        >>> preds = target + 0.1 * jnp.sin(2 * jnp.pi * 555 * t)
        >>> m = PerceptualEvaluationSpeechQuality(fs=8000, mode='nb')
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        4.4069
    """

    sum_pesq: Array
    total: Array
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -0.5
    plot_upper_bound: float = 4.5

    def __init__(
        self,
        fs: int,
        mode: str,
        n_processes: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        if mode == "wb" and fs == 8000:
            raise ValueError("Argument `mode='wb'` requires `fs=16000`")
        self.mode = mode
        if not isinstance(n_processes, int):
            raise ValueError(f"Expected argument `n_processes` to be an int but got {n_processes}")
        self.n_processes = n_processes

        self.add_state("sum_pesq", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-signal MOS-LQO (reference pesq.py:122-129)."""
        scores = perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode)
        self.sum_pesq = self.sum_pesq + jnp.nansum(scores)
        self.total = self.total + jnp.sum(~jnp.isnan(jnp.atleast_1d(scores)))

    def compute(self) -> Array:
        """Mean MOS-LQO (reference pesq.py:131-133)."""
        return self.sum_pesq / self.total


class ShortTimeObjectiveIntelligibility(Metric):
    """STOI averaged over all signals seen (reference audio/stoi.py:30-160).

    Example:
        >>> from torchmetrics_tpu.audio import ShortTimeObjectiveIntelligibility
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 8000.0)
        >>> target = jnp.sin(2 * jnp.pi * 440 * t)
        >>> preds = target + 0.1 * jnp.sin(2 * jnp.pi * 555 * t)
        >>> m = ShortTimeObjectiveIntelligibility(fs=8000)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        0.4694
    """

    sum_stoi: Array
    total: Array
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, fs: int, extended: bool = False, on_device: bool = False, **kwargs: Any) -> None:
        """``on_device=True`` (TPU extension) runs the jit/vmap-able float32 STOI
        pipeline so ``update`` can trace into a compiled step; the default host
        float64 path matches pystoi bit-for-bit."""
        super().__init__(**kwargs)
        if not isinstance(fs, int) or fs <= 0:
            raise ValueError(f"Expected argument `fs` to be a positive integer, but got {fs}")
        self.fs = fs
        if not isinstance(extended, bool):
            raise ValueError(f"Expected argument `extended` to be a bool, but got {extended}")
        self.extended = extended
        self.on_device = on_device

        self.add_state("sum_stoi", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-signal STOI (reference stoi.py:103-110)."""
        scores = short_time_objective_intelligibility(
            preds, target, self.fs, self.extended, on_device=self.on_device
        )
        self.sum_stoi = self.sum_stoi + jnp.sum(scores)
        self.total = self.total + jnp.atleast_1d(scores).size

    def compute(self) -> Array:
        """Mean STOI (reference stoi.py:112-114)."""
        return self.sum_stoi / self.total


class SpeechReverberationModulationEnergyRatio(Metric):
    """SRMR averaged over all signals seen (reference audio/srmr.py:33-187).

    Example:
        >>> from torchmetrics_tpu.audio import SpeechReverberationModulationEnergyRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = SpeechReverberationModulationEnergyRatio(fs=8000)
        >>> m.update(preds)
        >>> round(float(m.compute()), 4)
        67.7385
    """

    msum: Array
    total: Array
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        fs: int,
        n_cochlear_filters: int = 23,
        low_freq: float = 125,
        min_cf: float = 4,
        max_cf: Optional[float] = None,
        norm: bool = False,
        fast: bool = False,
        on_device: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _srmr_arg_validate(fs, n_cochlear_filters, low_freq, min_cf, max_cf, norm, fast)
        self.fs = fs
        self.n_cochlear_filters = n_cochlear_filters
        self.low_freq = low_freq
        self.min_cf = min_cf
        self.max_cf = max_cf
        self.norm = norm
        self.fast = fast
        self.on_device = on_device

        self.add_state("msum", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array) -> None:
        """Accumulate per-signal SRMR (reference srmr.py:136-143)."""
        scores = speech_reverberation_modulation_energy_ratio(
            preds,
            self.fs,
            n_cochlear_filters=self.n_cochlear_filters,
            low_freq=self.low_freq,
            min_cf=self.min_cf,
            max_cf=self.max_cf,
            norm=self.norm,
            fast=self.fast,
            on_device=self.on_device,
        )
        self.msum = self.msum + jnp.sum(scores)
        self.total = self.total + jnp.atleast_1d(scores).size

    def compute(self) -> Array:
        """Mean SRMR (reference srmr.py:145-147)."""
        return self.msum / self.total
