from torchmetrics_tpu.audio.pit import PermutationInvariantTraining  # noqa: F401
from torchmetrics_tpu.audio.sdr import (  # noqa: F401
    ScaleInvariantSignalDistortionRatio,
    SignalDistortionRatio,
    SourceAggregatedSignalDistortionRatio,
)
from torchmetrics_tpu.audio.dsp import (  # noqa: F401
    PerceptualEvaluationSpeechQuality,
    ShortTimeObjectiveIntelligibility,
    SpeechReverberationModulationEnergyRatio,
)
from torchmetrics_tpu.audio.snr import (  # noqa: F401
    ComplexScaleInvariantSignalNoiseRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalNoiseRatio,
)

__all__ = [
    "ComplexScaleInvariantSignalNoiseRatio",
    "PerceptualEvaluationSpeechQuality",
    "ShortTimeObjectiveIntelligibility",
    "SpeechReverberationModulationEnergyRatio",
    "PermutationInvariantTraining",
    "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio",
    "SignalDistortionRatio",
    "SignalNoiseRatio",
    "SourceAggregatedSignalDistortionRatio",
]
