"""Modular permutation-invariant training metric (reference audio/pit.py:30-130)."""
from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training
from torchmetrics_tpu.metric import Metric


class PermutationInvariantTraining(Metric):
    """Mean of the best-permutation metric value over all samples seen.

    Example:
        >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio
        >>> t = jnp.arange(0, 0.5, 1 / 800.0)
        >>> target = jnp.stack([jnp.sin(2 * jnp.pi * 100 * t), jnp.sin(2 * jnp.pi * 150 * t)])[None]
        >>> preds = target[:, ::-1, :] + 0.01 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = PermutationInvariantTraining(scale_invariant_signal_noise_ratio)
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        40.0014
    """

    full_state_update = False
    is_differentiable = True
    plot_lower_bound: float = -10.0
    plot_upper_bound: float = 10.0

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        base_kwargs = {
            k: kwargs.pop(k)
            for k in list(kwargs)
            if k
            in (
                "compute_on_cpu",
                "dist_sync_on_step",
                "sync_axis",
                "process_group",
                "dist_sync_fn",
                "distributed_available_fn",
                "sync_on_compute",
                "compute_with_cache",
            )
        }
        super().__init__(**base_kwargs)
        if eval_func not in ["max", "min"]:
            raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
        if mode not in ["speaker-wise", "permutation-wise"]:
            raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.kwargs = kwargs  # remaining kwargs forward to metric_func
        self.add_state("sum_pit_metric", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        pit_metric = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.kwargs
        )[0]
        self.sum_pit_metric = self.sum_pit_metric + jnp.sum(pit_metric)
        self.total = self.total + pit_metric.size

    def compute(self) -> Array:
        return self.sum_pit_metric / self.total
