"""Modular SDR metrics (reference audio/sdr.py:37-362): mean over all samples seen."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from torchmetrics_tpu.metric import Metric


class SignalDistortionRatio(Metric):
    """Signal Distortion Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import SignalDistortionRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = SignalDistortionRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        21.6639
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -20.0
    plot_upper_bound: float = 10.0

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag
        self.add_state("sum_sdr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sdr_batch = signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )
        self.sum_sdr = self.sum_sdr + jnp.sum(sdr_batch)
        self.total = self.total + sdr_batch.size

    def compute(self) -> Array:
        return self.sum_sdr / self.total


class ScaleInvariantSignalDistortionRatio(Metric):
    """Scale Invariant Signal Distortion Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 1.0, 1 / 800.0)
        >>> target = jnp.sin(2 * jnp.pi * 100 * t)
        >>> preds = target + 0.1 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = ScaleInvariantSignalDistortionRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        20.0
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -20.0
    plot_upper_bound: float = 10.0

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        si_sdr_batch = scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def compute(self) -> Array:
        return self.sum_si_sdr / self.total


class SourceAggregatedSignalDistortionRatio(Metric):
    """Source Aggregated Signal Distortion Ratio (modular interface, accumulating across updates).

    Example:
        >>> from torchmetrics_tpu.audio import SourceAggregatedSignalDistortionRatio
        >>> import jax.numpy as jnp
        >>> t = jnp.arange(0, 0.5, 1 / 800.0)
        >>> target = jnp.stack([jnp.sin(2 * jnp.pi * 100 * t), jnp.sin(2 * jnp.pi * 150 * t)])
        >>> preds = target + 0.05 * jnp.cos(2 * jnp.pi * 17 * t)
        >>> m = SourceAggregatedSignalDistortionRatio()
        >>> m.update(preds, target)
        >>> round(float(m.compute()), 4)
        26.0254
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = -20.0
    plot_upper_bound: float = 10.0

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        self.scale_invariant = scale_invariant
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean
        self.add_state("msdr_sum", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        msdr = source_aggregated_signal_distortion_ratio(preds, target, self.scale_invariant, self.zero_mean)
        self.msdr_sum = self.msdr_sum + jnp.sum(msdr)
        self.total = self.total + msdr.size

    def compute(self) -> Array:
        return self.msdr_sum / self.total
