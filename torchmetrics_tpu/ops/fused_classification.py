"""Fused multi-metric scatter-accumulate: the classification megakernel.

An accuracy + confusion-matrix + stat-scores collection shares ONE counting
core: every accumulator any of them lands is a slice of the task's confusion
counts. Unfused, each compute-group leader pays its own pass over
``(preds, target)`` — a bincount scatter for the confusion matrix, a second
identical scatter for tp/fp/tn/fn, masked boolean sums for the binary family
— all inside the same compiled collection dispatch. This module collapses
them: one shared confusion-count kernel per distinct ``(preds, target,
task-config)``, with every metric deriving its state update from slices of
that single result.

Fusion mechanism (ops/kernels.py :func:`~torchmetrics_tpu.ops.kernels
.shared_result`): within one trace, every compute-group leader receives the
*same* tracer objects for the batch, so the first leader builds the counting
kernel and the rest reuse its traced result — the compiled executable
contains exactly ONE scatter-accumulate launch (jaxpr-verified in
tests/test_kernels.py). The same identity memo serves the eager per-group
loop, the deferred ``shard_map`` epoch step, and the laned ``vmap`` dispatch,
where it composes with the PR 8 device row screen: the screen's predicate
and sentinel scatter evaluate in the same compiled dispatch as the fused
counts, so poisoned rows are diverted without a second pass.

The counting kernel itself is the ``"bincount"`` kernel behind the backend
dispatch seam: Pallas→Mosaic on TPU, Pallas→Triton on GPU, the masked XLA
scatter elsewhere (and as the parity oracle).

Exactness: counts are 0/1-weighted float32 sums — bit-exact integers up to
2**24 events per update (the same bound the confusion-matrix scatter always
had). Within that bound the fused path is bit-exact versus the unfused path
for every derived state, fused on or off (``TORCHMETRICS_TPU_FUSED_CLASSIFICATION=0``
restores the per-metric passes; the flag rides ``_trace_config()`` so the
two can never share a persisted executable).
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu import obs
from torchmetrics_tpu.ops import kernels

#: master switch for the fused classification family (default on); the
#: unfused path is the bit-exactness oracle and the A/B bench denominator
FUSED_ENV = "TORCHMETRICS_TPU_FUSED_CLASSIFICATION"


def fused_enabled() -> bool:
    return os.environ.get(FUSED_ENV, "1").strip().lower() not in ("0", "false", "off")


def _counts(idx: Array, w: Array, length: int) -> Array:
    """One scatter-accumulate pass: ``zeros(length).at[idx].add(w)`` through
    the backend-dispatched ``"bincount"`` kernel. ``checked=False``: every
    family helper zeroes masked targets and clips preds, so indices are
    in-range by construction and the reference body skips the drop mask."""
    return kernels.dispatch(
        "bincount", idx, w[None, :], length, n=int(idx.size), extent=int(length), checked=False
    )[0]


# ----------------------------------------------------------------- multiclass

def multiclass_confusion_counts(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int]
) -> Array:
    """(C, C) float32 confusion counts, shared across every multiclass metric
    tracing against the same ``(preds, target)``.

    Format semantics replicate both class paths exactly: score preds argmax
    over axis 1, everything flattened, ``ignore_index`` masked by weight,
    masked targets zeroed, preds clipped into range.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    spec = ("mc", int(num_classes), ignore_index)

    def build() -> Array:
        with obs.device_span(obs.SPAN_KERNEL, suffix="fused_classification"):
            p = preds.argmax(axis=1) if preds.ndim == target.ndim + 1 else preds
            p = p.reshape(-1)
            t = target.reshape(-1)
            if ignore_index is not None:
                w = (t != ignore_index).astype(jnp.float32)
                t = jnp.where(t == ignore_index, 0, t)
            else:
                w = jnp.ones_like(t, dtype=jnp.float32)
            t = t.astype(jnp.int32)
            p = jnp.clip(p.astype(jnp.int32), 0, num_classes - 1)
            idx = (num_classes * t + p).astype(jnp.int32)
            return _counts(idx, w, num_classes * num_classes).reshape(num_classes, num_classes)

    return kernels.shared_result((preds, target), spec, build)


def multiclass_stats(confmat: Array) -> Tuple[Array, Array, Array, Array]:
    """Per-class (tp, fp, tn, fn) int32 from (C, C) counts — the exact
    derivation the unfused stat-scores update performs on its own scatter."""
    tp = jnp.diagonal(confmat)
    fp = confmat.sum(0) - tp
    fn = confmat.sum(1) - tp
    tn = confmat.sum() - tp - fp - fn
    return (
        tp.astype(jnp.int32),
        fp.astype(jnp.int32),
        tn.astype(jnp.int32),
        fn.astype(jnp.int32),
    )


# --------------------------------------------------------------------- binary

def binary_confusion_counts(
    preds: Array, target: Array, threshold: float, ignore_index: Optional[int]
) -> Array:
    """(2, 2) float32 confusion counts shared across the binary family."""
    from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    spec = ("bin", float(threshold), ignore_index)

    def build() -> Array:
        with obs.device_span(obs.SPAN_KERNEL, suffix="fused_classification"):
            p = preds.reshape(-1)
            t = target.reshape(-1)
            if jnp.issubdtype(p.dtype, jnp.floating):
                p = (_sigmoid_if_logits(p) > threshold).astype(jnp.int32)
            else:
                p = jnp.clip(p.astype(jnp.int32), 0, 1)
            if ignore_index is not None:
                valid = t != ignore_index
                w = valid.astype(jnp.float32)
                t = jnp.where(valid, t, 0)
            else:
                w = jnp.ones_like(t, dtype=jnp.float32)
            idx = (t.astype(jnp.int32) * 2 + p).astype(jnp.int32)
            return _counts(idx, w, 4).reshape(2, 2)

    return kernels.shared_result((preds, target), spec, build)


def binary_stats(confmat: Array) -> Tuple[Array, Array, Array, Array]:
    """Scalar (tp, fp, tn, fn) int32 from the (2, 2) counts."""
    return (
        confmat[1, 1].astype(jnp.int32),
        confmat[0, 1].astype(jnp.int32),
        confmat[0, 0].astype(jnp.int32),
        confmat[1, 0].astype(jnp.int32),
    )


# ----------------------------------------------------------------- multilabel

def multilabel_confusion_counts(
    preds: Array, target: Array, num_labels: int, threshold: float, ignore_index: Optional[int]
) -> Array:
    """(L, 2, 2) float32 per-label confusion counts shared across the
    multilabel family."""
    from torchmetrics_tpu.functional.classification.stat_scores import _sigmoid_if_logits

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    spec = ("ml", int(num_labels), float(threshold), ignore_index)

    def build() -> Array:
        with obs.device_span(obs.SPAN_KERNEL, suffix="fused_classification"):
            p = preds
            if jnp.issubdtype(p.dtype, jnp.floating):
                p = (_sigmoid_if_logits(p) > threshold).astype(jnp.int32)
            p = jnp.moveaxis(p, 1, -1).reshape(-1, num_labels)
            t = jnp.moveaxis(target, 1, -1).reshape(-1, num_labels)
            if ignore_index is not None:
                valid = t != ignore_index
                w = valid.astype(jnp.float32)
                t = jnp.where(valid, t, 0)
                p = jnp.where(valid, p, 0)
            else:
                w = jnp.ones_like(t, dtype=jnp.float32)
            p = jnp.clip(p.astype(jnp.int32), 0, 1)
            label_idx = jnp.arange(num_labels)[None, :]
            idx = (label_idx * 4 + t.astype(jnp.int32) * 2 + p).astype(jnp.int32)
            return _counts(idx.reshape(-1), w.reshape(-1), num_labels * 4).reshape(num_labels, 2, 2)

    return kernels.shared_result((preds, target), spec, build)


def multilabel_stats(confmat: Array) -> Tuple[Array, Array, Array, Array]:
    """Per-label (tp, fp, tn, fn) int32 from the (L, 2, 2) counts."""
    return (
        confmat[:, 1, 1].astype(jnp.int32),
        confmat[:, 0, 1].astype(jnp.int32),
        confmat[:, 0, 0].astype(jnp.int32),
        confmat[:, 1, 0].astype(jnp.int32),
    )
