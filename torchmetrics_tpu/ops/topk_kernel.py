"""Pallas kernel: fused top-k retrieval statistics over the padded query grid.

The padded retrieval design (functional/retrieval/_padded.py) evaluates every
metric as masked reductions over one static ``(Q, L)`` ranked-target grid. A
retrieval collection (precision@k + recall@k + fall-out@k + hit-rate@k) pays
four separate masked passes over that grid; the four reductions share the
same masks, so one fused sweep lands them all:

    [hits@k, total_relevant, inverse_hits@k, total_inverse]  per query.

Registered as kernel ``"retrieval_topk_stats"``. The grid is parallel over
query tiles (each program writes its own rows), so one body serves both the
Mosaic and Triton lowerings. The reference body is the exact jnp expressions
the padded kernels always used; with 0/1 relevance the counts are exact
integers in f32, so the fused path is bit-exact against it.

The shared-result memo in ops/kernels.py deduplicates the sweep across
metrics reading the same ranked grid in one trace (or one eager loop) —
the same mechanism as the classification megakernel.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from torchmetrics_tpu.ops import kernels

TILE_Q = 8  # query rows per program (f32 sublane alignment)
_OUT_COLS = 128  # lane-aligned output row; 4 used


def _topk_stats_kernel(t_ref, c_ref, out_ref, *, top_k: int):
    t = t_ref[:]  # (TILE_Q, Lp)
    c = c_ref[:].reshape(TILE_Q, 1)  # (TILE_Q, 1) int32
    pos = jax.lax.broadcasted_iota(jnp.int32, t.shape, 1)
    valid = (pos < c).astype(jnp.float32)
    k = c if top_k < 0 else jnp.minimum(top_k, c)
    mask = (pos < k).astype(jnp.float32)
    inv = (1.0 - t) * valid
    stats = jnp.stack(
        [
            (t * mask).sum(axis=1),  # hits in the top k (padding is 0-target)
            t.sum(axis=1),  # total relevant
            (inv * mask).sum(axis=1),  # non-relevant retrieved in the top k
            inv.sum(axis=1),  # total non-relevant
        ],
        axis=1,
    )  # (TILE_Q, 4)
    out_ref[:] = jnp.pad(stats, ((0, 0), (0, _OUT_COLS - stats.shape[1])))


@functools.partial(jax.jit, static_argnames=("top_k", "interpret"))
def _topk_stats_pallas(
    ranked_target: Array, counts: Array, top_k: int, interpret: bool = False
) -> Array:
    q, length = ranked_target.shape
    q_pad = -q % TILE_Q
    l_pad = -length % 128
    t = jnp.pad(ranked_target.astype(jnp.float32), ((0, q_pad), (0, l_pad)))
    c = jnp.pad(counts.astype(jnp.int32), (0, q_pad))  # pad count 0 -> all-invalid rows
    num_q_tiles = (q + q_pad) // TILE_Q

    out = pl.pallas_call(
        functools.partial(_topk_stats_kernel, top_k=top_k),
        grid=(num_q_tiles,),
        in_specs=[
            pl.BlockSpec((TILE_Q, length + l_pad), lambda qi: (qi, 0)),
            pl.BlockSpec((TILE_Q,), lambda qi: (qi,)),
        ],
        out_specs=pl.BlockSpec((TILE_Q, _OUT_COLS), lambda qi: (qi, 0)),
        out_shape=jax.ShapeDtypeStruct((q + q_pad, _OUT_COLS), jnp.float32),
        interpret=interpret,
    )(t, c)
    return out[:q, :4]


@functools.partial(jax.jit, static_argnames=("top_k",))
def _topk_stats_reference(ranked_target: Array, counts: Array, top_k: int) -> Array:
    """The padded kernels' exact jnp expressions, fused into one (Q, 4) row."""
    t = ranked_target.astype(jnp.float32)
    pos = jnp.arange(t.shape[-1])[None, :]
    c = counts[:, None]
    k = c if top_k < 0 else jnp.minimum(top_k, c)
    mask = (pos < k).astype(t.dtype)
    inv = jnp.where(pos < c, 1.0 - t, 0.0)
    return jnp.stack(
        [
            jnp.sum(t * mask, axis=-1),
            jnp.sum(t, axis=-1),
            jnp.sum(inv * mask, axis=-1),
            jnp.sum(inv, axis=-1),
        ],
        axis=1,
    )


kernels.register_kernel(
    kernels.KernelSpec(
        name="retrieval_topk_stats",
        reference=lambda t, c, top_k, interpret=False: _topk_stats_reference(t, c, top_k),
        tpu=_topk_stats_pallas,
        triton=_topk_stats_pallas,
        # one (TILE_Q, Lp) tile must sit resident; Lp caps at the VMEM /
        # shared-memory budget (GPU row provisional until a capture)
        min_n={"tpu": 1 << 16, "triton": 1 << 15},
        max_extent={"tpu": 1 << 15, "triton": 1 << 13},
        doc="per-query [hits@k, total_rel, inv_hits@k, total_inv] in one sweep",
    )
)


def retrieval_topk_stats(
    ranked_target: Array, counts: Array, top_k: Optional[int], interpret: bool = False
) -> Array:
    """(Q, 4) ``[hits@k, total_rel, inv_hits@k, total_inv]`` through the seam,
    memoized on the identity of ``(ranked_target, counts)`` so every padded
    retrieval metric reading the same grid in one trace shares one sweep.

    ``top_k=None`` selects each query's full document list (the per-query
    count), matching ``_topk_mask``.
    """
    ranked_target = jnp.asarray(ranked_target)
    counts = jnp.asarray(counts)
    k = -1 if top_k is None else int(top_k)

    def build() -> Array:
        return kernels.dispatch(
            "retrieval_topk_stats",
            ranked_target,
            counts,
            k,
            n=int(ranked_target.size),
            extent=int(ranked_target.shape[-1]),
            interpret=interpret,
        )

    return kernels.shared_result((ranked_target, counts), ("topk", k), build)
