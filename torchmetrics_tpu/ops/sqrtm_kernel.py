"""Pallas kernel: PSD matrix square root for the FID trace term.

FID's compute is ``Tr sqrt(S1 S2)`` via the symmetric identity
``sqrt(S1^1/2 S2 S1^1/2)`` (image/fid.py): the expensive half is the PSD
square root ``S1^1/2`` of the F×F covariance (768² at the standard Inception
tap). General eigendecomposition maps poorly onto the MXU; the Newton–Schulz
coupled iteration is nothing but matmuls, so the whole solve fits ONE Pallas
launch with Y/Z resident in VMEM:

    Y_0 = A / c,  Z_0 = I,  c = ||A||_F
    T_k = (3 I - Z_k Y_k) / 2
    Y_{k+1} = Y_k T_k,   Z_{k+1} = T_k Z_k
    sqrt(A) ≈ Y_K * sqrt(c)

Registered as kernel ``"fid_sqrtm"``. The reference body is the eigh-based
PSD-projected square root the FID compute always used (exact, and the parity
oracle); the NS iteration is an APPROXIMATION (documented: ~1e-4 relative
after 16 iterations on covariance-conditioned inputs), which is why the gate
keeps the reference body everywhere until an accelerator capture justifies
the trade. Padding to the 128-lane grid carries an identity block
(``sqrt(diag(A, I)) = diag(sqrt(A), I)``), so the padded iteration is exact
in the padded region and the slice-back loses nothing.

The last named leftover of the PR 11 megakernel pass (ROADMAP "Kernel pass
leftovers").
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from torchmetrics_tpu.ops import kernels

#: Newton–Schulz iterations: 16 lands ~1e-4 relative on covariance-shaped
#: spectra while staying a fixed, jit-static launch
NS_ITERS = 16

_LANE = 128


def _eye(n: int, dtype=jnp.float32) -> Array:
    rows = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return (rows == cols).astype(dtype)


def _sqrtm_ns_kernel(a_ref, out_ref, *, iters: int):
    a = a_ref[:].astype(jnp.float32)
    n = a.shape[0]
    eye = _eye(n)
    c = jnp.maximum(jnp.sqrt(jnp.sum(a * a)), 1e-30)
    y = a / c
    z = eye

    def body(_, yz):
        y, z = yz
        t = 0.5 * (3.0 * eye - jnp.dot(z, y, preferred_element_type=jnp.float32))
        return (
            jnp.dot(y, t, preferred_element_type=jnp.float32),
            jnp.dot(t, z, preferred_element_type=jnp.float32),
        )

    y, _ = jax.lax.fori_loop(0, iters, body, (y, z))
    out_ref[:] = y * jnp.sqrt(c)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _sqrtm_pallas(sigma: Array, interpret: bool = False) -> Array:
    f = sigma.shape[0]
    pad = -f % _LANE
    a = jnp.pad(sigma.astype(jnp.float32), ((0, pad), (0, pad)))
    if pad:
        # identity in the pad block: sqrt(diag(A, I)) = diag(sqrt(A), I), so
        # the padded iteration stays exact and well-conditioned
        idx = jnp.arange(f + pad)
        pad_diag = jnp.where(idx >= f, 1.0, 0.0)
        a = a + jnp.diag(pad_diag)
    out = pl.pallas_call(
        functools.partial(_sqrtm_ns_kernel, iters=NS_ITERS),
        out_shape=jax.ShapeDtypeStruct((f + pad, f + pad), jnp.float32),
        interpret=interpret,
    )(a)
    return out[:f, :f].astype(sigma.dtype)


@jax.jit
def _sqrtm_reference(sigma: Array) -> Array:
    """The eigh-based PSD-projected square root (image/fid.py's original
    expression — exact on every backend, and the parity oracle)."""
    e, v = jnp.linalg.eigh(sigma)
    return (v * jnp.sqrt(jnp.clip(e, 0.0, None))) @ v.T


kernels.register_kernel(
    kernels.KernelSpec(
        name="fid_sqrtm",
        reference=lambda sigma, interpret=False: _sqrtm_reference(sigma),
        tpu=_sqrtm_pallas,
        triton=_sqrtm_pallas,
        # Y/Z/T triple must sit VMEM-resident: F=1024 → ~12.6 MB f32 working
        # set. Both gate rows are PROVISIONAL estimates (no accelerator
        # capture yet — ROADMAP "Kernel pass leftovers"); min_n keeps small
        # covariances (fast exact eigh) off the iterative path
        min_n={"tpu": 256 * 256, "triton": 256 * 256},
        max_extent={"tpu": 1024, "triton": 1024},
        doc="PSD matrix sqrt via in-VMEM Newton-Schulz (FID trace term)",
    )
)


def sqrtm_psd(sigma: Array, interpret: bool = False) -> Array:
    """``sigma^(1/2)`` for a symmetric PSD matrix through the backend seam.

    ``n`` is the element count F², ``extent`` the matrix edge F — the gate
    falls back to the exact eigh reference for small/huge problems and on
    backends without a Pallas body (CPU always).
    """
    sigma = jnp.asarray(sigma)
    return kernels.dispatch(
        "fid_sqrtm",
        sigma,
        n=int(sigma.size),
        extent=int(sigma.shape[-1]),
        interpret=interpret,
    )
