"""Pallas kernel: fused weighted bincount (deterministic scatter-add).

The counting core of the classification stack — confusion matrices
(``num_classes*target + preds`` flattened indices), binned PR-curve states and
calibration histograms all reduce to ``zeros(L).at[idx].add(w)``. XLA lowers
that to a serialized scatter on TPU; this kernel instead tiles the index
stream against the bin axis and accumulates per-tile one-hot partial sums in
VMEM — an embarrassingly parallel compare+reduce the VPU is built for, with a
(TILE_N, TILE_C) working set that never leaves on-chip memory.

Two lowerings of the same tile body (registered as kernel ``"bincount"`` in
the ops/kernels.py dispatch seam):

- **Mosaic (TPU)**: grid ``(num_bin_tiles, num_index_tiles)`` with the index
  axis minormost — each output tile stays VMEM-resident while every index
  tile streams past it (the revisited-output reduction pattern, which relies
  on the TPU grid being sequential).
- **Triton (GPU)**: one program per bin tile, index tiles consumed by an
  in-kernel ``fori_loop`` — Triton grids run concurrently, so the reduction
  must live inside the program instead of across grid steps. Tile sizes are
  provisional until a GPU capture tunes them.

Out-of-range indices contribute nothing (they match no bin tile) — the same
drop semantics as the masked XLA reference body.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from torchmetrics_tpu.ops import kernels

TILE_N = 1024  # indices per step (Mosaic)
TILE_C = 512  # bins per output tile (multiple of 128 lanes)
TRITON_TILE_N = 1024  # indices per loop iteration (Triton; provisional)
TRITON_TILE_C = 128  # bins per program (Triton; provisional)


def _onehot_partial(x: Array, w: Array, ci, tile_n: int, tile_c: int) -> Array:
    """The shared tile body: one-hot the index tile against bin tile ``ci``
    and contract all K weight rows against it in a single
    (K, tile_n) @ (tile_n, tile_c) matmul on the MXU/tensor cores."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (tile_n, tile_c), 1) + ci * tile_c
    onehot = (x.reshape(tile_n, 1) == cols).astype(jnp.float32)
    return jnp.dot(w, onehot, preferred_element_type=jnp.float32)


def _wbincount_kernel(x_ref, w_ref, out_ref):
    ci = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += _onehot_partial(x_ref[:], w_ref[:], ci, TILE_N, TILE_C)


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _wbincount_pallas(x: Array, weights: Array, length: int, interpret: bool = False) -> Array:
    """weights (K, N) -> counts (K, length); one index sweep for all K rows."""
    k, n = weights.shape
    n_pad = -n % TILE_N
    c_pad = -length % TILE_C
    k_pad = -k % 8  # sublane-aligned weight rows
    # padded indices point outside every bin tile -> dropped
    x = jnp.pad(x.astype(jnp.int32), (0, n_pad), constant_values=-1)
    w = jnp.pad(weights.astype(jnp.float32), ((0, k_pad), (0, n_pad)))
    num_c_tiles = (length + c_pad) // TILE_C
    num_n_tiles = (n + n_pad) // TILE_N

    out = pl.pallas_call(
        _wbincount_kernel,
        grid=(num_c_tiles, num_n_tiles),
        in_specs=[
            pl.BlockSpec((TILE_N,), lambda ci, ni: (ni,)),
            pl.BlockSpec((k + k_pad, TILE_N), lambda ci, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((k + k_pad, TILE_C), lambda ci, ni: (0, ci)),
        out_shape=jax.ShapeDtypeStruct((k + k_pad, num_c_tiles * TILE_C), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[:k, :length]


def _wbincount_kernel_triton(x_ref, w_ref, out_ref, *, num_n_tiles: int, k: int):
    ci = pl.program_id(0)

    def body(ni, acc):
        x = x_ref[pl.ds(ni * TRITON_TILE_N, TRITON_TILE_N)]
        w = w_ref[:, pl.ds(ni * TRITON_TILE_N, TRITON_TILE_N)]
        return acc + _onehot_partial(x, w, ci, TRITON_TILE_N, TRITON_TILE_C)

    out_ref[:] = jax.lax.fori_loop(
        0, num_n_tiles, body, jnp.zeros((k, TRITON_TILE_C), jnp.float32)
    )


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _wbincount_triton(x: Array, weights: Array, length: int, interpret: bool = False) -> Array:
    """The Triton lowering: bin tiles across programs, index loop inside."""
    k, n = weights.shape
    n_pad = -n % TRITON_TILE_N
    c_pad = -length % TRITON_TILE_C
    x = jnp.pad(x.astype(jnp.int32), (0, n_pad), constant_values=-1)
    w = jnp.pad(weights.astype(jnp.float32), ((0, 0), (0, n_pad)))
    num_c_tiles = (length + c_pad) // TRITON_TILE_C
    num_n_tiles = (n + n_pad) // TRITON_TILE_N

    out = pl.pallas_call(
        functools.partial(_wbincount_kernel_triton, num_n_tiles=num_n_tiles, k=k),
        grid=(num_c_tiles,),
        in_specs=[
            pl.BlockSpec((n + n_pad,), lambda ci: (0,)),
            pl.BlockSpec((k, n + n_pad), lambda ci: (0, 0)),
        ],
        out_specs=pl.BlockSpec((k, TRITON_TILE_C), lambda ci: (0, ci)),
        out_shape=jax.ShapeDtypeStruct((k, num_c_tiles * TRITON_TILE_C), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[:, :length]


@functools.partial(jax.jit, static_argnames=("length", "checked"))
def _wbincount_reference(x: Array, weights: Array, length: int, checked: bool = True) -> Array:
    """Pure-XLA fallback and parity oracle: masked scatter-add.

    ``checked=True`` drops out-of-range indices explicitly to match the
    kernels (jnp's scatter wraps negatives numpy-style even under
    mode="drop"); callers whose indices are in-range by construction (the
    fused classification counts: targets zeroed, preds clipped) pass
    ``checked=False`` and skip the mask. K==1 stays a 1-D scatter — the
    batched (K, L) scatter lowers ~35% slower on CPU for the single-row case
    that dominates the classification hot path."""
    w = weights.astype(jnp.float32)
    if checked:
        in_range = (x >= 0) & (x < length)
        x = jnp.where(in_range, x, 0)
        w = jnp.where(in_range[None, :], w, 0.0)
    if weights.shape[0] == 1:
        return jnp.zeros(int(length), dtype=jnp.float32).at[x].add(w[0])[None, :]
    return jnp.zeros((weights.shape[0], int(length)), dtype=jnp.float32).at[:, x].add(w)


kernels.register_kernel(
    kernels.KernelSpec(
        name="bincount",
        # the Pallas bodies drop out-of-range indices by construction (they
        # match no bin tile), so ``checked`` only parameterizes the reference
        reference=lambda x, w, length, interpret=False, checked=True: _wbincount_reference(
            x, w, length, checked=checked
        ),
        tpu=lambda x, w, length, interpret=False, checked=True: _wbincount_pallas(
            x, w, length, interpret=interpret
        ),
        triton=lambda x, w, length, interpret=False, checked=True: _wbincount_triton(
            x, w, length, interpret=interpret
        ),
        # measured on v5e: 3-6.4x faster than XLA's serialized scatter for
        # length <= 2048 at N >= 1e5-1e7, slower beyond ~4096 bins. The GPU
        # row is a provisional estimate (Triton one-hot matmuls win earlier,
        # shared memory caps the resident bin tile) pending a capture.
        min_n={"tpu": 1 << 16, "triton": 1 << 15},
        max_extent={"tpu": 2048, "triton": 4096},
        doc="zeros(L).at[idx].add(w) over K weight rows sharing one index stream",
    )
)


def weighted_bincount(
    x: Array,
    weights: Array | None = None,
    length: int = 0,
    interpret: bool = False,
) -> Array:
    """``zeros(length).at[x].add(weights)`` through the kernel dispatch seam.

    The backend (TPU Pallas / GPU Triton / XLA reference), the problem-size
    gates and their env overrides (``TORCHMETRICS_TPU_PALLAS_MIN_N``,
    ``TORCHMETRICS_TPU_PALLAS_MAX_EXTENT``) all live in ops/kernels.py; the
    decision taken for each call is recorded in the gate log surfaced via
    ``executor_status["kernels"]``. Returns float32 when weighted, int32
    otherwise.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.ops.bincount import weighted_bincount
        >>> weighted_bincount(jnp.asarray([0, 1, 1, 3]), length=4).tolist()
        [1, 2, 0, 1]
        >>> weighted_bincount(jnp.asarray([0, 1, 1, 3]),
        ...                   weights=jnp.asarray([0.5, 1.0, 2.0, 0.25]), length=4).tolist()
        [0.5, 3.0, 0.0, 0.25]
    """
    x = jnp.asarray(x).ravel()
    weighted = weights is not None
    w = jnp.asarray(weights).ravel() if weighted else jnp.ones(x.shape, dtype=jnp.float32)
    out = kernels.dispatch(
        "bincount",
        x,
        w[None, :],
        int(length),
        n=int(x.size),
        extent=int(length),
        interpret=interpret,
    )[0]
    return out if weighted else out.astype(jnp.int32)


def weighted_bincount_multi(
    x: Array,
    weights: Array,
    length: int,
    interpret: bool = False,
) -> Array:
    """K weighted bincounts sharing one index stream: weights (K, N) -> (K, length).

    One VMEM sweep builds each one-hot tile once and contracts it against all
    K weight rows on the MXU (vs K separate scatter passes) — calibration's
    count/confidence/accuracy histograms are the canonical K=3 use.
    """
    x = jnp.asarray(x).ravel()
    weights = jnp.asarray(weights, dtype=jnp.float32)
    if weights.ndim != 2 or weights.shape[1] != x.shape[0]:
        raise ValueError(f"weights must be (K, N={x.shape[0]}), got {weights.shape}")
    return kernels.dispatch(
        "bincount",
        x,
        weights,
        int(length),
        n=int(x.size),
        extent=int(length),
        interpret=interpret,
    )
