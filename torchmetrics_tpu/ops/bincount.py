"""Pallas TPU kernel: fused weighted bincount (deterministic scatter-add).

The counting core of the classification stack — confusion matrices
(``num_classes*target + preds`` flattened indices), binned PR-curve states and
calibration histograms all reduce to ``zeros(L).at[idx].add(w)``. XLA lowers
that to a serialized scatter on TPU; this kernel instead tiles the index
stream against the bin axis and accumulates per-tile one-hot partial sums in
VMEM — an embarrassingly parallel compare+reduce the VPU is built for, with a
(TILE_N, TILE_C) working set that never leaves on-chip memory.

Grid layout: ``(num_bin_tiles, num_index_tiles)`` with the index axis
minormost, so each output tile stays resident in VMEM while every index tile
streams past it (standard revisited-output reduction pattern).

Out-of-range indices contribute nothing (they match no bin tile) — the same
drop semantics as jnp's default scatter mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

TILE_N = 1024  # indices per step
TILE_C = 512  # bins per output tile (multiple of 128 lanes)


def _wbincount_kernel(x_ref, w_ref, out_ref):
    # multi-weight variant: K weight rows share one index stream; the one-hot
    # tile is built once and contracted against all rows in a single
    # (K, TILE_N) @ (TILE_N, TILE_C) matmul on the MXU
    ci = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:].reshape(TILE_N, 1)  # (TILE_N, 1) int32
    w = w_ref[:]  # (K, TILE_N) f32
    cols = jax.lax.broadcasted_iota(jnp.int32, (TILE_N, TILE_C), 1) + ci * TILE_C
    onehot = (x == cols).astype(jnp.float32)  # (TILE_N, TILE_C)
    out_ref[:] += jnp.dot(w, onehot, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _wbincount_pallas(x: Array, weights: Array, length: int, interpret: bool = False) -> Array:
    """weights (K, N) -> counts (K, length); one index sweep for all K rows."""
    k, n = weights.shape
    n_pad = -n % TILE_N
    c_pad = -length % TILE_C
    k_pad = -k % 8  # sublane-aligned weight rows
    # padded indices point outside every bin tile -> dropped
    x = jnp.pad(x.astype(jnp.int32), (0, n_pad), constant_values=-1)
    w = jnp.pad(weights.astype(jnp.float32), ((0, k_pad), (0, n_pad)))
    num_c_tiles = (length + c_pad) // TILE_C
    num_n_tiles = (n + n_pad) // TILE_N

    out = pl.pallas_call(
        _wbincount_kernel,
        grid=(num_c_tiles, num_n_tiles),
        in_specs=[
            pl.BlockSpec((TILE_N,), lambda ci, ni: (ni,)),
            pl.BlockSpec((k + k_pad, TILE_N), lambda ci, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((k + k_pad, TILE_C), lambda ci, ni: (0, ci)),
        out_shape=jax.ShapeDtypeStruct((k + k_pad, num_c_tiles * TILE_C), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[:k, :length]


def weighted_bincount(
    x: Array,
    weights: Array | None = None,
    length: int = 0,
    interpret: bool = False,
    min_pallas_n: int = 1 << 16,
    max_pallas_length: int = 2048,
) -> Array:
    """``zeros(length).at[x].add(weights)`` with a Pallas fast path on TPU.

    The kernel does dense one-hot work (O(N·length)), so it is dispatched only
    in the regime where that beats XLA's serialized scatter — measured on
    v5e: 3-6.4x faster for length <= 2048 at N >= 1e5-1e7, slower beyond
    ~4096 bins. Binned PR-curve states (4·T bins), calibration histograms and
    small-to-medium confusion matrices all live in the winning regime.
    Falls back to XLA's scatter-add off-TPU, for small N, or for large bin
    counts. Returns float32 when weighted, int32 otherwise.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.ops.bincount import weighted_bincount
        >>> weighted_bincount(jnp.asarray([0, 1, 1, 3]), length=4).tolist()
        [1, 2, 0, 1]
        >>> weighted_bincount(jnp.asarray([0, 1, 1, 3]),
        ...                   weights=jnp.asarray([0.5, 1.0, 2.0, 0.25]), length=4).tolist()
        [0.5, 3.0, 0.0, 0.25]
    """
    x = jnp.asarray(x).ravel()
    weighted = weights is not None
    w = jnp.asarray(weights).ravel() if weighted else jnp.ones(x.shape, dtype=jnp.float32)
    # axon (the remote-TPU plugin) also registers its backend as "tpu", but
    # accept both names defensively
    use_pallas = interpret or (
        jax.default_backend() in ("tpu", "axon")
        and x.size >= min_pallas_n
        and length <= max_pallas_length
    )
    if use_pallas:
        out = _wbincount_pallas(x, w[None, :], int(length), interpret=interpret)[0]
    else:
        # drop out-of-range indices explicitly to match the kernel: jnp's
        # scatter wraps negatives numpy-style even under mode="drop"
        in_range = (x >= 0) & (x < length)
        out = (
            jnp.zeros(int(length), dtype=jnp.float32)
            .at[jnp.where(in_range, x, 0)]
            .add(jnp.where(in_range, w, 0.0))
        )
    return out if weighted else out.astype(jnp.int32)


def weighted_bincount_multi(
    x: Array,
    weights: Array,
    length: int,
    interpret: bool = False,
    min_pallas_n: int = 1 << 16,
    max_pallas_length: int = 2048,
) -> Array:
    """K weighted bincounts sharing one index stream: weights (K, N) -> (K, length).

    One VMEM sweep builds each one-hot tile once and contracts it against all
    K weight rows on the MXU (vs K separate scatter passes) — calibration's
    count/confidence/accuracy histograms are the canonical K=3 use.
    """
    x = jnp.asarray(x).ravel()
    weights = jnp.asarray(weights, dtype=jnp.float32)
    if weights.ndim != 2 or weights.shape[1] != x.shape[0]:
        raise ValueError(f"weights must be (K, N={x.shape[0]}), got {weights.shape}")
    use_pallas = interpret or (
        jax.default_backend() in ("tpu", "axon")
        and x.size >= min_pallas_n
        and length <= max_pallas_length
    )
    if use_pallas:
        return _wbincount_pallas(x, weights, int(length), interpret=interpret)
    in_range = (x >= 0) & (x < length)
    xs = jnp.where(in_range, x, 0)
    ws = jnp.where(in_range[None, :], weights, 0.0)
    return jnp.zeros((weights.shape[0], int(length)), dtype=jnp.float32).at[:, xs].add(ws)
