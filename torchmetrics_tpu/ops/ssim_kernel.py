"""Pallas kernel: fused separable windowed statistics (the SSIM core).

SSIM's windowed moments run the 5-stacked image batch (preds, target, preds²,
target², preds·target) through a separable window — two banded-matrix GEMMs
(functional/image/utils.py ``_separable_window_2d``). Stock lowering
materialises the (M, Ho, Wp) intermediate between the H-pass and the W-pass
in HBM; this kernel keeps one image's working set VMEM-resident and runs both
contractions back-to-back per grid step, so the intermediate never leaves
on-chip memory.

Registered as kernel ``"ssim_windows"`` in the ops/kernels.py seam. The grid
is embarrassingly parallel (one program per stacked image plane, each writing
its own output block), so the SAME body serves the Mosaic (TPU) and Triton
(GPU) lowerings — only the extent gates differ (VMEM vs shared-memory
budgets). The reference body is the einsum pair the GEMM path always used,
kept bit-identical for the off-accelerator dispatch.

Float contractions: fused and reference paths agree to f32 matmul
accumulation order, not bitwise — the parity suite bounds the difference at
a few ulps (integer-count exactness is a classification-megakernel property,
not an SSIM one).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from torchmetrics_tpu.ops import kernels


def _window_kernel(x_ref, bh_ref, bw_ref, out_ref):
    x = x_ref[0]  # (Hp, Wp)
    # both contractions in VMEM; HIGHEST keeps full-f32 MXU passes — the
    # E[x^2]-mu^2 cancellation downstream cannot survive bf16 truncation
    tmp = jnp.dot(
        bh_ref[:].T, x, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST
    )  # (Ho, Wp)
    out_ref[0] = jnp.dot(
        tmp, bw_ref[:], preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _windowed_pallas(x: Array, bh: Array, bw: Array, interpret: bool = False) -> Array:
    """x (M, Hp, Wp), bh (Hp, Ho), bw (Wp, Wo) -> (M, Ho, Wo)."""
    m, hp, wp = x.shape
    ho, wo = bh.shape[1], bw.shape[1]
    return pl.pallas_call(
        _window_kernel,
        grid=(m,),
        in_specs=[
            pl.BlockSpec((1, hp, wp), lambda i: (i, 0, 0)),
            pl.BlockSpec((hp, ho), lambda i: (0, 0)),
            pl.BlockSpec((wp, wo), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, ho, wo), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, ho, wo), jnp.float32),
        interpret=interpret,
    )(x.astype(jnp.float32), bh.astype(jnp.float32), bw.astype(jnp.float32))


@jax.jit
def _windowed_reference(x: Array, bh: Array, bw: Array) -> Array:
    """The einsum pair of the GEMM path, on the stacked (M, Hp, Wp) layout —
    identical contraction order to the pre-seam ``_separable_window_2d``."""
    out = jnp.einsum("mhw,hi->miw", x, bh.astype(x.dtype), precision=jax.lax.Precision.HIGHEST)
    return jnp.einsum("miw,wj->mij", out, bw.astype(x.dtype), precision=jax.lax.Precision.HIGHEST)


kernels.register_kernel(
    kernels.KernelSpec(
        name="ssim_windows",
        reference=lambda x, bh, bw, interpret=False: _windowed_reference(x, bh, bw),
        tpu=_windowed_pallas,
        triton=_windowed_pallas,
        # per-plane VMEM working set: x + intermediate + banded matrices;
        # 512² f32 triple-buffers inside 16 MB. Triton's shared-memory budget
        # caps the resident plane lower (provisional until a GPU capture).
        min_n={"tpu": 1 << 18, "triton": 1 << 18},
        max_extent={"tpu": 512, "triton": 256},
        doc="fused separable banded-window contraction for SSIM moment stacks",
    )
)


def windowed_sum_2d(x: Array, bh: Array, bw: Array, interpret: bool = False) -> Array:
    """Separable windowed sum of a stacked (M, Hp, Wp) plane batch through the
    kernel seam: ``x_padded @ banded(g_h) @ banded(g_w)`` per plane."""
    return kernels.dispatch(
        "ssim_windows",
        x,
        bh,
        bw,
        n=int(x.size),
        extent=int(max(x.shape[1], x.shape[2])),
        interpret=interpret,
    )
