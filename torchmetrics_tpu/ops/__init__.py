"""Pallas TPU kernels for the framework's hot ops.

These sit below the functional layer: XLA already fuses most of the compute
path well, so kernels live here only where a hand-tiled VMEM-resident loop
beats the default lowering (SURVEY §7: "pallas kernels for the hot ops").
Every kernel has an XLA fallback and is dispatched by backend + problem size.
"""
from torchmetrics_tpu.ops.bincount import weighted_bincount, weighted_bincount_multi  # noqa: F401
from torchmetrics_tpu.ops.binned_curve import binned_curve_counts, binned_curve_counts_classwise  # noqa: F401

__all__ = ["binned_curve_counts", "binned_curve_counts_classwise", "weighted_bincount", "weighted_bincount_multi"]
