"""Pallas kernels for the framework's hot ops (docs/KERNELS.md).

These sit below the functional layer: XLA already fuses most of the compute
path well, so kernels live here only where a hand-tiled VMEM-resident loop
beats the default lowering (SURVEY §7: "pallas kernels for the hot ops").
Every kernel registers THREE bodies in the ops/kernels.py backend dispatch
seam — a Pallas→Mosaic TPU lowering, a Pallas→Triton GPU lowering, and the
pure-XLA reference that doubles as the interpret-mode parity oracle — and is
selected per process by backend + problem-size gates (env-overridable), with
the decision recorded in the gate log behind ``executor_status["kernels"]``.
"""
from torchmetrics_tpu.ops.bincount import weighted_bincount, weighted_bincount_multi  # noqa: F401
from torchmetrics_tpu.ops.binned_curve import binned_curve_counts, binned_curve_counts_classwise  # noqa: F401
from torchmetrics_tpu.ops.kernels import (  # noqa: F401
    gate_snapshot,
    registered_kernels,
    resolve_backend,
)
from torchmetrics_tpu.ops.sqrtm_kernel import sqrtm_psd  # noqa: F401
from torchmetrics_tpu.ops.ssim_kernel import windowed_sum_2d  # noqa: F401
from torchmetrics_tpu.ops.topk_kernel import retrieval_topk_stats  # noqa: F401

__all__ = [
    "binned_curve_counts",
    "binned_curve_counts_classwise",
    "gate_snapshot",
    "registered_kernels",
    "resolve_backend",
    "retrieval_topk_stats",
    "sqrtm_psd",
    "weighted_bincount",
    "weighted_bincount_multi",
    "windowed_sum_2d",
]
