"""Pallas TPU kernel: fused binned threshold-curve state update.

The binned PR-curve/ROC update (reference precision_recall_curve.py:211-226)
builds ``preds_t = preds >= thresholds`` of shape (T, N) in HBM before
scatter-adding into the (T, 2, 2) state — for N=2M, T=200 that materialises
~3 GB of traffic and dominates the step. This kernel streams preds/target
tiles through VMEM, does the threshold compare + masked count per tile
entirely on-chip, and accumulates the (T, 4) counts in a resident output
block: the (T, N) intermediate never exists.

Measured on v5e at N=2M, T=200: 7 ms/step vs 972 ms for the
materialise+scatter lowering (~140x). Driver-grade capture (BENCH_r04,
bench config 6, N=1M T=100): 185.9 steps/s end-to-end = 81.8x the torch
reference baseline. Off-TPU the update lowers to a searchsorted +
suffix-sum path (O(N log T)) instead — see `_binned_counts_searchsorted`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from torchmetrics_tpu.ops import kernels

TILE_N = 1024  # 1-D f32 operands must match XLA's (1024)-tiled layout
MAX_T = 1024  # (TILE_N, T_pad) f32 working set must fit VMEM (4 MB)
_OUT_ROWS = 8  # sublane-aligned output rows; 4 used (bins p + 2t)


def _binned_tile(p, t, v, thr):
    """Shared tile body: threshold compare + masked count for one index tile,
    returning the (8, T_pad) partial-count update (rows [t0p0,t0p1,t1p0,t1p1])."""
    pred_t = (p >= thr).astype(jnp.float32)  # (tile, T_pad)
    pos = t * v  # target==1 weight column
    neg = (1.0 - t) * v
    row1 = (pred_t * neg).sum(axis=0)  # t=0, p=1
    row3 = (pred_t * pos).sum(axis=0)  # t=1, p=1
    n_neg = neg.sum()
    n_pos = pos.sum()
    # Mosaic has no scatter-add: assemble the full (8, T_pad) update by rows
    return jnp.concatenate(
        [
            (n_neg - row1)[None, :],  # t=0, p=0
            row1[None, :],
            (n_pos - row3)[None, :],  # t=1, p=0
            row3[None, :],
            jnp.zeros((_OUT_ROWS - 4,) + row1.shape, dtype=row1.dtype),
        ],
        axis=0,
    )


def _binned_kernel(p_ref, t_ref, v_ref, thr_ref, out_ref):
    # Mosaic schedule: revisited-output reduction over the (sequential) grid
    ni = pl.program_id(0)

    @pl.when(ni == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += _binned_tile(
        p_ref[:].reshape(TILE_N, 1),
        t_ref[:].reshape(TILE_N, 1),
        v_ref[:].reshape(TILE_N, 1),
        thr_ref[:],  # (1, T_pad)
    )


def _binned_kernel_triton(p_ref, t_ref, v_ref, thr_ref, out_ref, *, num_n_tiles, t_pad_len):
    # Triton schedule: grid programs run concurrently, so the reduction loops
    # over index tiles INSIDE the single program instead of across grid steps
    thr = thr_ref[:]

    def body(ni, acc):
        sl = pl.ds(ni * TILE_N, TILE_N)
        return acc + _binned_tile(
            p_ref[sl].reshape(TILE_N, 1),
            t_ref[sl].reshape(TILE_N, 1),
            v_ref[sl].reshape(TILE_N, 1),
            thr,
        )

    out_ref[:] = jax.lax.fori_loop(
        0, num_n_tiles, body, jnp.zeros((_OUT_ROWS, t_pad_len), jnp.float32)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counts_pallas(preds: Array, target: Array, valid: Array, thresholds: Array, interpret: bool = False) -> Array:
    n = preds.shape[0]
    len_t = thresholds.shape[0]
    n_pad = -n % TILE_N
    t_pad = -len_t % 128
    preds = jnp.pad(preds.astype(jnp.float32), (0, n_pad))
    target = jnp.pad(target.astype(jnp.float32), (0, n_pad))
    valid = jnp.pad(valid.astype(jnp.float32), (0, n_pad))  # pad weight 0 -> no counts
    thr = jnp.pad(thresholds.astype(jnp.float32), (0, t_pad)).reshape(1, len_t + t_pad)
    num_n_tiles = (n + n_pad) // TILE_N

    out = pl.pallas_call(
        _binned_kernel,
        grid=(num_n_tiles,),
        in_specs=[
            pl.BlockSpec((TILE_N,), lambda ni: (ni,)),
            pl.BlockSpec((TILE_N,), lambda ni: (ni,)),
            pl.BlockSpec((TILE_N,), lambda ni: (ni,)),
            pl.BlockSpec((1, len_t + t_pad), lambda ni: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_OUT_ROWS, len_t + t_pad), lambda ni: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_OUT_ROWS, len_t + t_pad), jnp.float32),
        interpret=interpret,
    )(preds, target, valid, thr)
    # rows [t0p0, t0p1, t1p0, t1p1] -> (T, 2, 2)[t, p]
    return out[:4, :len_t].T.reshape(len_t, 2, 2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counts_triton(preds: Array, target: Array, valid: Array, thresholds: Array, interpret: bool = False) -> Array:
    n = preds.shape[0]
    len_t = thresholds.shape[0]
    n_pad = -n % TILE_N
    t_pad = -len_t % 128
    preds = jnp.pad(preds.astype(jnp.float32), (0, n_pad))
    target = jnp.pad(target.astype(jnp.float32), (0, n_pad))
    valid = jnp.pad(valid.astype(jnp.float32), (0, n_pad))  # pad weight 0 -> no counts
    thr = jnp.pad(thresholds.astype(jnp.float32), (0, t_pad)).reshape(1, len_t + t_pad)
    num_n_tiles = (n + n_pad) // TILE_N

    full = pl.BlockSpec((n + n_pad,), lambda: (0,))
    out = pl.pallas_call(
        functools.partial(_binned_kernel_triton, num_n_tiles=num_n_tiles, t_pad_len=len_t + t_pad),
        grid=(),
        in_specs=[full, full, full, pl.BlockSpec((1, len_t + t_pad), lambda: (0, 0))],
        out_specs=pl.BlockSpec((_OUT_ROWS, len_t + t_pad), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_OUT_ROWS, len_t + t_pad), jnp.float32),
        interpret=interpret,
    )(preds, target, valid, thr)
    return out[:4, :len_t].T.reshape(len_t, 2, 2)


kernels.register_kernel(
    kernels.KernelSpec(
        name="binned_curve",
        reference=lambda p, t, v, thr, interpret=False: _binned_counts_searchsorted(p, t, v, thr),
        tpu=_binned_counts_pallas,
        triton=_binned_counts_triton,
        # v5e measurement: 7 ms vs 972 ms at N=2M, T=200 (~140x); the GPU row
        # is provisional until a Triton capture tunes it. MAX_T bounds the
        # VMEM/shared-memory-resident (TILE_N, T_pad) working set.
        min_n={"tpu": 1 << 15, "triton": 1 << 14},
        max_extent={"tpu": MAX_T, "triton": MAX_T},
        doc="(T, 2, 2) threshold-binned confusion counts in one fused sweep",
    )
)


def binned_curve_counts(
    preds: Array,
    target: Array,
    valid: Array,
    thresholds: Array,
    interpret: bool = False,
) -> Array:
    """(T, 2, 2) threshold-binned confusion counts through the kernel seam.

    ``valid`` is the per-sample weight (0 masks ignore_index samples).
    Backend selection and the size gates (env-overridable) live in
    ops/kernels.py; off-TPU/GPU, for small N or large T the searchsorted +
    suffix-sum reference body runs instead.
    """
    preds = jnp.asarray(preds).ravel()
    target = jnp.asarray(target).ravel()
    valid = jnp.asarray(valid).ravel()
    thresholds = jnp.asarray(thresholds)
    return kernels.dispatch(
        "binned_curve",
        preds,
        target,
        valid,
        thresholds,
        n=int(preds.size),
        extent=int(thresholds.shape[0]),
        interpret=interpret,
    )


@jax.jit
def binned_curve_counts_classwise(preds: Array, pos_w: Array, neg_w: Array, thresholds: Array) -> Array:
    """(T, C, 2, 2) per-column threshold-binned counts, O(N·C·log T).

    Each of the C columns (one-vs-rest classes or labels) gets its own
    (T, 2, 2) count block from a single bucketing pass + suffix sum (see
    ``_binned_counts_searchsorted`` for the algorithm). ``pos_w``/``neg_w`` are
    the per-sample-per-column positive/negative weights (already masked for
    ignore_index). Preferred off-TPU over the (T, N, C) one-hot materialization
    used by the MXU bincount path.
    """
    n, c = preds.shape
    len_t = thresholds.shape[0]
    order = jnp.argsort(thresholds)
    thr_sorted = thresholds[order]
    k = jnp.searchsorted(thr_sorted, preds.ravel(), side="right")
    k = jnp.where(jnp.isnan(preds.ravel()), 0, k)
    col = jnp.broadcast_to(jnp.arange(c), (n, c)).ravel()
    idx = k * c + col  # bucket-major so the (T+1, C) reshape is direct
    w = jnp.stack([neg_w.astype(jnp.float32).ravel(), pos_w.astype(jnp.float32).ravel()])
    hist = jnp.zeros((2, (len_t + 1) * c), dtype=jnp.float32).at[:, idx].add(w)
    hist = hist.reshape(2, len_t + 1, c)
    totals = hist.sum(axis=1, keepdims=True)  # (2, 1, C)
    pred1_sorted = totals - jnp.cumsum(hist, axis=1)[:, :len_t]  # (2, T, C)
    pred1 = jnp.zeros_like(pred1_sorted).at[:, order].set(pred1_sorted)
    pred0 = jnp.broadcast_to(totals, pred1.shape) - pred1
    # (2 target, T, C) x (2 pred) -> (T, C, 2 target, 2 pred)
    return jnp.stack([pred0, pred1], axis=-1).transpose(1, 2, 0, 3)


@jax.jit
def _binned_counts_searchsorted(preds: Array, target: Array, valid: Array, thresholds: Array) -> Array:
    """O(N log T) fallback: bucket each sample once, then suffix-sum over bins.

    ``pred >= thr[t]`` holds exactly for the first ``k`` sorted thresholds,
    where ``k = searchsorted(thr, pred, 'right')`` — so one histogram of ``k``
    plus a reversed cumulative sum yields the positive count at every
    threshold simultaneously. Replaces the old (T, N) one-hot contraction
    (O(N·T) work and memory; 2x slower than torch's bincount path at N=1M on
    CPU — round-3 bench config 6) with two O(N) scatter-adds.
    Single-column case of :func:`binned_curve_counts_classwise`.
    """
    tgt = target.astype(jnp.float32) * valid.astype(jnp.float32)
    neg = (1.0 - target.astype(jnp.float32)) * valid.astype(jnp.float32)
    return binned_curve_counts_classwise(preds[:, None], tgt[:, None], neg[:, None], thresholds)[:, 0]
