"""Backend dispatch seam for the Pallas kernel layer (docs/KERNELS.md).

Every hand-written kernel in ``ops/`` registers here with THREE bodies:

- ``tpu``:       the Pallas body lowered through Mosaic (TPU),
- ``triton``:    the same Pallas body lowered through Pallas's Triton backend
                 (GPU) — usually the identical ``pallas_call`` with
                 GPU-friendly tile parameters,
- ``reference``: the pure-XLA fallback, which is also the parity oracle every
                 registered kernel is tested against in interpret mode
                 (tests/test_kernels.py) and the body every other backend
                 (CPU, METAL, ...) runs.

:func:`dispatch` selects the body by the default JAX backend plus a
backend-aware problem-size gate, so callers never hand-roll
``jax.default_backend() == "tpu"`` checks again. The decision is recorded in
a process-global gate log (surfaced through ``Metric.executor_status`` under
``"kernels"`` and via ``gate_snapshot()``) and counted into the obs registry
(``kernels.pallas_dispatches`` / ``kernels.triton_dispatches`` /
``kernels.xla_fallbacks``) so a bench run can attribute which path actually
served it. Under ``jit`` the selection happens at trace time — the counters
count *selections* (one per compiled executable per kernel site), while eager
call sites count once per call; both attribute the path, which is what the
bench needs.

The executor's persistent-cache key already pins ``backend/device_kind``
(ops/compile_cache.py ``backend_fingerprint``), so a Triton lowering lands in
its own disk-cache partition with zero new cache machinery — GPU is a new
partition, not a new architecture (docs/EXECUTOR.md).

Shared-intermediate memo: :func:`shared_result` lets several metrics in one
trace (or one eager per-group loop) reuse a single kernel result computed
from the *same* input arrays — the mechanism behind the fused classification
megakernel (ops/fused_classification.py) and the fused retrieval top-k stats.
Keys are identity-verified (``entry arrays are the call's arrays``), so stale
tracers from a dead trace can never leak into a live one.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from torchmetrics_tpu import obs

#: env override for the minimum problem size (elements of the streamed axis)
#: below which every kernel falls back to its pure-XLA reference body
MIN_N_ENV = "TORCHMETRICS_TPU_PALLAS_MIN_N"
#: env override for the maximum output extent (bins / thresholds / window dim)
#: above which the VMEM-resident tiling stops paying
MAX_EXTENT_ENV = "TORCHMETRICS_TPU_PALLAS_MAX_EXTENT"
#: force a backend: "tpu" | "triton" | "xla" | "auto" (default)
BACKEND_ENV = "TORCHMETRICS_TPU_KERNEL_BACKEND"

_COUNTER_BY_PATH = {
    "tpu": "kernels.pallas_dispatches",
    "triton": "kernels.triton_dispatches",
    "xla": "kernels.xla_fallbacks",
}


@dataclass
class KernelSpec:
    """One registered kernel: three bodies plus per-backend gates.

    ``min_n`` / ``max_extent`` map backend name → threshold; a backend absent
    from the map uses the ``"default"`` entry. ``None`` disables the bound.
    """

    name: str
    reference: Callable[..., Any]
    tpu: Optional[Callable[..., Any]] = None
    triton: Optional[Callable[..., Any]] = None
    min_n: Dict[str, Optional[int]] = field(default_factory=dict)
    max_extent: Dict[str, Optional[int]] = field(default_factory=dict)
    doc: str = ""

    def gate(self, backend: str, kind: str) -> Optional[int]:
        table = self.min_n if kind == "min_n" else self.max_extent
        if backend in table:
            return table[backend]
        return table.get("default")


_REGISTRY: Dict[str, KernelSpec] = {}
_GATE_LOG: Dict[str, Dict[str, Any]] = {}
_GATE_LOCK = threading.Lock()


def register_kernel(spec: KernelSpec) -> KernelSpec:
    """Register (or re-register) a kernel under ``spec.name``."""
    _REGISTRY[spec.name] = spec
    return spec


def get_kernel(name: str) -> KernelSpec:
    return _REGISTRY[name]


def registered_kernels() -> Dict[str, KernelSpec]:
    """Live registry view — the static pallas_call check and docs read this."""
    return dict(_REGISTRY)


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def resolve_backend() -> str:
    """The kernel backend the current process dispatches to.

    ``"tpu"`` (Pallas→Mosaic) when the default backend is a TPU (axon — the
    remote-TPU plugin — also registers as "tpu" but is matched by name
    defensively), ``"triton"`` (Pallas→Triton) on GPU backends, ``"xla"``
    (reference body) everywhere else. ``TORCHMETRICS_TPU_KERNEL_BACKEND``
    forces a specific answer — useful to pin the reference body on a TPU for
    an A/B, or to exercise the Triton gate table off-GPU.
    """
    forced = os.environ.get(BACKEND_ENV, "auto").strip().lower()
    if forced in ("tpu", "triton", "xla"):
        return forced
    platform = jax.default_backend()
    if platform in ("tpu", "axon"):
        return "tpu"
    if platform in ("gpu", "cuda", "rocm"):
        return "triton"
    return "xla"


def _record_gate(name: str, decision: Dict[str, Any]) -> None:
    with _GATE_LOCK:
        entry = _GATE_LOG.setdefault(name, {"selections": {}})
        entry.update(decision)
        path = decision.get("path")
        entry["selections"][path] = entry["selections"].get(path, 0) + 1
    # the flight recorder's kernels domain replays the last N gate decisions
    # after a fault (bounded deque append; never raises into the dispatch)
    obs.flight_note("kernels", name, **decision)


def gate_snapshot() -> Dict[str, Dict[str, Any]]:
    """Last gate decision + per-path selection counts for every kernel that
    has dispatched in this process — the bench's path-attribution record
    (surfaced under ``executor_status["kernels"]``)."""
    with _GATE_LOCK:
        return {k: dict(v, selections=dict(v["selections"])) for k, v in _GATE_LOG.items()}


def reset_gate_log() -> None:
    with _GATE_LOCK:
        _GATE_LOG.clear()


def dispatch(
    name: str,
    *args: Any,
    n: int,
    extent: int = 0,
    interpret: bool = False,
    **kwargs: Any,
) -> Any:
    """Run kernel ``name`` through the backend-selected body.

    ``n`` is the streamed problem size (elements swept), ``extent`` the
    resident output extent (bins / thresholds / window edge) — both static
    Python ints under jit, which is exactly when the gate must decide.
    ``interpret=True`` forces the TPU Pallas body in interpreter mode (the
    parity-suite hook); it bypasses the size gates so small test problems
    still exercise the kernel body.
    """
    spec = _REGISTRY[name]
    if interpret:
        body, path, reason = spec.tpu, "tpu", "interpret"
        kwargs["interpret"] = True
    else:
        backend = resolve_backend()
        body, path, reason = spec.reference, "xla", f"backend={backend}"
        if backend in ("tpu", "triton"):
            candidate = spec.tpu if backend == "tpu" else spec.triton
            min_n = spec.gate(backend, "min_n")
            env_min = _env_int(MIN_N_ENV)
            if env_min is not None:
                min_n = env_min
            max_extent = spec.gate(backend, "max_extent")
            env_max = _env_int(MAX_EXTENT_ENV)
            if env_max is not None:
                max_extent = env_max
            if candidate is None:
                reason = f"no {backend} body"
            elif min_n is not None and n < min_n:
                reason = f"n={n} below min_n={min_n}"
            elif max_extent is not None and extent > max_extent:
                reason = f"extent={extent} above max_extent={max_extent}"
            else:
                body, path, reason = candidate, backend, "gates passed"
    _record_gate(name, {"path": path, "reason": reason, "n": int(n), "extent": int(extent)})
    obs.counter_inc(_COUNTER_BY_PATH[path])
    with obs.device_span(obs.SPAN_KERNEL, suffix=name):
        return body(*args, **kwargs)


# ------------------------------------------------------ shared-result memo
#
# A tiny identity-keyed cache letting several metrics traced (or run eagerly)
# against the SAME input arrays share one kernel result. A hit requires every
# key array to `is`-match, so a reused Python id can never satisfy a lookup.
#
# Two stores, by input kind:
#
# - CONCRETE arrays memoize in a bounded process-global LRU (entries pin only
#   arrays — cheap, and the eager per-group collection loop needs reuse to
#   survive across member update calls).
# - TRACERS memoize only inside an active :func:`shared_scope` frame, popped
#   when the enclosing trace finishes. A tracer entry references its trace,
#   which references the traced closure and (for executor builds) the metric
#   itself — parking that in a process-global cache would pin dead metrics
#   and their executors past GC (caught by the telemetry executor-release
#   test). Without an active scope, tracer results are simply not memoized.
#   The scope stack is thread-local: background-compile workers trace
#   concurrently with the main thread.

_MEMO_MAX = 16
_MEMO: "OrderedDict[Tuple[Any, ...], Tuple[Tuple[Any, ...], Any]]" = OrderedDict()
_MEMO_LOCK = threading.Lock()
_SCOPES = threading.local()


class shared_scope:
    """One fusion scope: tracer-keyed shared results live exactly as long as
    the ``with`` block (the collection trace / eager round) that opened it.
    Nests; inner lookups see outer frames (an outer trace's tracer is valid
    inside an inner one, the reverse never `is`-matches)."""

    def __enter__(self) -> "shared_scope":
        stack = getattr(_SCOPES, "stack", None)
        if stack is None:
            stack = _SCOPES.stack = []
        stack.append({})
        return self

    def __exit__(self, *exc: Any) -> None:
        _SCOPES.stack.pop()


def _is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def shared_result(arrays: Tuple[Any, ...], spec: Tuple[Any, ...], builder: Callable[[], Any]) -> Any:
    """``builder()`` memoized on the identity of ``arrays`` + a config tuple.

    The fusion primitive: inside one traced collection step every
    compute-group leader receives the *same* tracer objects for
    (preds, target), so the first leader builds the shared accumulator kernel
    and the rest reuse its (traced) result — the compiled executable contains
    ONE kernel launch. Eager per-group loops get the same saving with
    concrete arrays through the LRU.
    """
    key = tuple(id(a) for a in arrays) + tuple(spec)
    if any(_is_tracer(a) for a in arrays):
        stack = getattr(_SCOPES, "stack", None)
        if not stack:
            obs.counter_inc("kernels.fused_builds")
            return builder()
        for frame in reversed(stack):
            hit = frame.get(key)
            if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
                obs.counter_inc("kernels.fused_reuses")
                return hit[1]
        value = builder()
        stack[-1][key] = (tuple(arrays), value)
        obs.counter_inc("kernels.fused_builds")
        return value

    with _MEMO_LOCK:
        hit = _MEMO.get(key)
        if hit is not None and all(a is b for a, b in zip(hit[0], arrays)):
            _MEMO.move_to_end(key)
            obs.counter_inc("kernels.fused_reuses")
            return hit[1]
    value = builder()
    with _MEMO_LOCK:
        _MEMO[key] = (tuple(arrays), value)
        _MEMO.move_to_end(key)
        while len(_MEMO) > _MEMO_MAX:
            _MEMO.popitem(last=False)
    obs.counter_inc("kernels.fused_builds")
    return value


def clear_shared_results() -> None:
    """Drop every memoized shared result (tests; never required for
    correctness — identity verification already rejects stale entries)."""
    with _MEMO_LOCK:
        _MEMO.clear()
