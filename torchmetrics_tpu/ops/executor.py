"""Donated-state jitted executor for the eager stateful API (L2/L4).

The pure functional path (``functional_update`` inside a user's jitted train
step) has always enjoyed fused XLA execution; the stateful shell
(``Metric.update()``, ``forward()``, ``MetricCollection`` in a plain eval loop)
dispatched op-by-op from Python. This module closes that gap: every eager
``update``/``forward`` call looks up (or builds) a compiled function

    state' = f(state, *batch)

keyed by ``(call kind, input pytree structure, shape bucket, dtypes)`` with the
state pytree **donated** (``donate_argnums=0``), so large accumulators
(capacity-buffered curves, confusion matrices, feature buffers) are updated in
place instead of copied every step.

Shape bucketing
    Ragged batches (the last batch of an epoch) are padded up a small geometric
    ladder of power-of-two buckets so they reuse the warm executable instead of
    triggering a recompile. Padding rows are copies of the batch's first row;
    inside the trace the padded contribution is subtracted exactly for
    ``"sum"``-reduced states (duplicated real rows are no-ops for ``max``/
    ``min`` states). The correction assumes the update is per-sample additive,
    which the executor *verifies empirically*: the first padded call for a
    metric also runs the eager op-by-op oracle and compares; on any mismatch
    bucketing is disabled for that instance (exact-shape compilation remains).

Donation ownership
    Donating a buffer invalidates every other reference to it, so the executor
    only donates arrays it itself produced and that have not escaped to user
    code since. ``Metric`` tracks two flags:

    - ``_state_escaped`` — some state array may be referenced outside the
      metric (a ``state()`` export, an attribute read, a fresh ``reset`` whose
      arrays alias ``_defaults``). The next executor call copies the state
      once, then re-owns the result.
    - ``_state_shared`` — the arrays are aliased *by design* inside a
      ``MetricCollection`` compute group. The single-metric executor never
      donates shared state; the collection's fused executor manages the group
      as a whole.

    The first call on a fresh cache key also copies, so a compile-time failure
    can never consume live state.

Escape hatch
    ``Metric(..., executor=False)`` / ``MetricCollection(..., executor=False)``
    or the environment variable ``TORCHMETRICS_TPU_EXECUTOR=0`` restore the
    previous eager op-by-op path exactly; any error while tracing a metric's
    update falls back to the eager path permanently for that instance (the
    reason is recorded in :func:`executor_stats`).

Synced path
    :func:`make_synced_collection_step` builds the fused
    ``update -> sync -> compute`` step used under ``shard_map``: the
    collection-level leaf fusion in ``parallel/sync.py`` coalesces the whole
    collection's collectives into one ``psum`` per (reduction, dtype) per step,
    and computed values are packed into one replicated buffer per dtype so an
    N-metric collection pays O(dtypes), not O(N), per-output dispatch cost.
"""
from __future__ import annotations

import os
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utils.exceptions import DispatchStallError
from torchmetrics_tpu.utils.prints import rank_zero_debug

# CPU (and some other) backends do not implement buffer donation; jax warns on
# every dispatch. Donation is still semantically correct there (silently
# ignored), so silence exactly that message.
warnings.filterwarnings("ignore", message="Some donated buffers were not usable")

ENV_FLAG = "TORCHMETRICS_TPU_EXECUTOR"
#: set to "0" to drop the per-call host-side recovery snapshot taken before a
#: donating dispatch (docs/EXECUTOR.md "Failure semantics") — faster steady
#: state, but a failed dispatch then resets the metric instead of restoring it
RECOVERY_ENV_FLAG = "TORCHMETRICS_TPU_EXECUTOR_RECOVERY"

#: reserved key carried by ``Metric.state()`` exports (see metric.py)
STATE_COUNT_KEY = "_update_count"

_BUCKET_FLOOR = 8
_FUSABLE_REDUCTIONS = ("sum", "max", "min")
_PY_SCALARS = (bool, int, float, complex, np.generic)


def executor_enabled_default() -> bool:
    """Global default from the environment (``TORCHMETRICS_TPU_EXECUTOR``)."""
    return os.environ.get(ENV_FLAG, "1").strip().lower() not in ("0", "false", "off", "no")


def recovery_enabled_default() -> bool:
    """Whether donating calls keep a host-side recovery snapshot
    (``TORCHMETRICS_TPU_EXECUTOR_RECOVERY``, on by default)."""
    return os.environ.get(RECOVERY_ENV_FLAG, "1").strip().lower() not in ("0", "false", "off", "no")


class _DispatchFailure(Exception):
    """Internal: a WARM executable failed at dispatch time.

    By then the inputs may already have been donated, so the executor has
    restored the live state (from the host-side recovery snapshot) before
    raising this; the outer entry point unwraps and propagates ``original`` to
    the caller instead of falling back to the eager body — the eager body
    would silently re-run the batch and turn an error into a double-count
    hazard, and a transient runtime failure must not permanently disable the
    compiled path the way a trace failure does.
    """

    def __init__(self, original: BaseException) -> None:
        super().__init__(str(original))
        self.original = original


def bucket_size(n: int) -> int:
    """Next rung of the geometric bucket ladder: powers of two, floor 8.

    >>> [bucket_size(n) for n in (1, 8, 9, 100, 1024)]
    [8, 8, 16, 128, 1024]
    """
    n = int(n)
    if n <= _BUCKET_FLOOR:
        return _BUCKET_FLOOR
    return 1 << (n - 1).bit_length()


_trace_probe_logged = False


def _trace_clean() -> bool:
    global _trace_probe_logged
    try:
        return bool(jax.core.trace_state_clean())
    except Exception as err:
        # jax moved/renamed this probe across versions; assume an untraced
        # context but say so once instead of silently guessing forever
        if not _trace_probe_logged:
            _trace_probe_logged = True
            rank_zero_debug(
                f"torchmetrics_tpu executor: jax.core.trace_state_clean unavailable"
                f" ({type(err).__name__}: {err}); assuming untraced context"
            )
        return True


def _is_concrete_array(x: Any) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) and not isinstance(x, jax.core.Tracer)


def _classify_leaves(leaves: Sequence[Any]):
    """Per-leaf signature, or None when any leaf cannot cross a jit boundary.

    Python ``bool`` leaves key on their VALUE: they stay static (closed over
    per executable) so flag arguments like FID's ``update(imgs, real=True)``
    keep driving Python control flow instead of becoming tracers.
    """
    sig: List[Any] = []
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            return None
        if type(leaf) is bool:
            sig.append(("static_bool", leaf))
        elif _is_concrete_array(leaf):
            arr = leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
            sig.append((tuple(arr.shape), str(arr.dtype)))
        elif isinstance(leaf, _PY_SCALARS):
            sig.append(("py", type(leaf).__name__))
        else:
            return None
    return tuple(sig)


def _split_static_bools(leaves: Sequence[Any]):
    """(dynamic leaves, ((index, value), ...)) — bools are closed over, not traced."""
    dyn: List[Any] = []
    spec: List[Tuple[int, bool]] = []
    for i, leaf in enumerate(leaves):
        if type(leaf) is bool:
            spec.append((i, leaf))
        else:
            dyn.append(leaf)
    return dyn, tuple(spec)


def _merge_static_bools(dyn: Sequence[Any], spec: Tuple[Tuple[int, bool], ...], total: int) -> List[Any]:
    fixed = dict(spec)
    it = iter(dyn)
    return [fixed[i] if i in fixed else next(it) for i in range(total)]


def _common_batch_dim(leaves: Sequence[Any]) -> Optional[int]:
    """The shared leading dim of every >=1-d array leaf, if one exists."""
    dims = set()
    for leaf in leaves:
        if _is_concrete_array(leaf) and getattr(leaf, "ndim", 0) >= 1:
            dims.add(int(leaf.shape[0]))
    if len(dims) != 1:
        return None
    return dims.pop()


def _pad_leaves(leaves: Sequence[Any], batched: Sequence[bool], pad_to: int) -> List[Any]:
    """Pad each batched leaf's leading dim to ``pad_to`` with copies of row 0."""
    out: List[Any] = []
    for leaf, is_batched in zip(leaves, batched):
        if not is_batched:
            out.append(leaf)
            continue
        arr = jnp.asarray(leaf)
        n = arr.shape[0]
        if n == pad_to:
            out.append(arr)
        else:
            fill = jnp.broadcast_to(arr[:1], (pad_to - n,) + arr.shape[1:])
            out.append(jnp.concatenate([arr, fill], axis=0))
    return out


def _row0_leaves(leaves: Sequence[Any], batched: Sequence[bool]) -> List[Any]:
    return [leaf[:1] if is_batched else leaf for leaf, is_batched in zip(leaves, batched)]


def _tree_copy(state: Dict[str, Any]) -> Dict[str, Any]:
    return {k: jnp.array(v, copy=True) for k, v in state.items()}


def _states_close(a: Dict[str, Any], b: Dict[str, Any], fields) -> bool:
    for k in fields:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        if x.shape != y.shape:
            return False
        if np.issubdtype(x.dtype, np.floating):
            if not np.allclose(x, y, rtol=1e-4, atol=1e-6, equal_nan=True):
                return False
        elif not np.array_equal(x, y):
            return False
    return True


def _values_close(a: Any, b: Any) -> bool:
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or not np.allclose(x, y, rtol=1e-4, atol=1e-6, equal_nan=True):
            return False
    return True


def _subtract_pad_contribution(
    metric: Any,
    updated: Dict[str, Any],
    defaults: Dict[str, Any],
    init_const: Dict[str, Any],
    row0_args: tuple,
    row0_kwargs: dict,
    extra: Any,
) -> Dict[str, Any]:
    """Remove the padding rows' contribution from an updated state pytree.

    ``extra`` (traced scalar) is the number of padded rows, each a copy of the
    batch's first row. For per-sample-additive ``"sum"`` states the padding
    adds exactly ``extra * (update(init, row0) - default)``; duplicated real
    rows can never change a ``max``/``min`` state. Validity is probed
    empirically on the first padded call (see module docstring).
    """
    d1 = metric.functional_update(init_const, *row0_args, **row0_kwargs)
    out: Dict[str, Any] = {}
    for field in metric._defaults:
        if metric._reductions.get(field) == "sum":
            contrib = d1[field] - defaults[field]
            out[field] = updated[field] - contrib * extra.astype(jnp.asarray(contrib).dtype)
        else:
            out[field] = updated[field]
    return out


def _new_stats() -> Dict[str, Any]:
    return {
        "calls": 0,          # executor actually ran the computation
        "compiles": 0,       # distinct cache keys built (one XLA compile each)
        "cache_hits": 0,     # calls served by a warm executable
        "padded_calls": 0,   # calls that padded a ragged batch up the ladder
        "donated_calls": 0,  # calls that donated the live state buffers
        "copied_calls": 0,   # calls that copied first (escaped/shared/fresh key)
        "probes": 0,         # eager oracle runs validating padded execution
        "skipped_calls": 0,  # per-call ineligibility (tracers, odd inputs)
        "dispatch_failures": 0,   # warm-executable failures propagated to the caller
        "recovery_restores": 0,   # donated states reinstalled from the host snapshot
        "dispatch_retries": 0,    # warm failures re-attempted after the restore (io/retry.py)
    }


class _ExecutorBase:
    """Shared cache/stats/flag plumbing for metric- and collection-executors."""

    def __init__(self) -> None:
        self._cache: Dict[Any, Callable] = {}
        self.stats = _new_stats()
        self.disabled_reason: Optional[str] = None
        self._static_reason_cached: Any = ()  # sentinel: not yet computed
        self._pad_validated = False
        self._bucketing_ok = True
        self._keep_recovery = recovery_enabled_default()
        # most recent committed donating call's host-side recovery snapshot,
        # kept so the Autosaver (io/checkpoint.py) can serialize it instead of
        # fetching the live state again — zero extra device sync per autosave.
        # MetricExecutor: (described_update_count, {field: np}); Collection:
        # {leader: (count, {field: np})}. None when the last call copied.
        self._last_recovery: Any = None

    def _owner_name(self) -> str:
        return type(self).__name__

    def _disable(self, reason: str) -> None:
        """Permanently fall back to the eager path, RECORDING why (ISSUE 2
        satellite: a metric silently running 20× slower must be diagnosable).
        The reason surfaces via ``Metric.executor_status`` /
        :func:`executor_stats` and is logged once at debug level."""
        if self.disabled_reason is None:
            rank_zero_debug(
                f"torchmetrics_tpu executor disabled for {self._owner_name()}: {reason}"
                " (eager fallback; see Metric.executor_status)"
            )
        self.disabled_reason = reason

    def _snapshot(self, state: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Host-side recovery reference taken right before a donating call: if
        the dispatch dies after the runtime took the buffers, this is the only
        surviving copy of the accumulated state. ``None`` when recovery is
        disabled via the env flag.

        ``np.array`` (copying) rather than ``jax.device_get``: on CPU backends
        device_get can return a zero-copy VIEW of the device buffer, which an
        in-place donating dispatch then overwrites — silently corrupting the
        very snapshot that exists to survive it."""
        if not self._keep_recovery:
            return None
        return {k: np.array(v) for k, v in state.items()}

    def _restore(self, metric: Any, recovery: Optional[Dict[str, Any]]) -> None:
        """Reinstall a recovery snapshot (or defaults when recovery is off)
        into ``metric`` after a donated dispatch failed."""
        if recovery is not None:
            restored = {k: jnp.asarray(v) for k, v in recovery.items()}
            self.stats["recovery_restores"] += 1
        else:
            restored = {k: jnp.asarray(v) for k, v in metric._defaults.items()}
            rank_zero_debug(
                f"torchmetrics_tpu executor: dispatch failed after donation with"
                f" {RECOVERY_ENV_FLAG}=0 — state of {type(metric).__name__} reset to defaults"
            )
        new_state = dict(metric._state)
        new_state.update(restored)
        object.__setattr__(metric, "_state", new_state)
        metric.__dict__["_state_escaped"] = True

    def _guarded_dispatch(
        self,
        primary: Callable[[], Any],
        retry_call: Callable[[], Any],
        fresh: bool,
        restore: Callable[[], None],
    ) -> Any:
        """Run a compiled dispatch under the stall watchdog with transient-
        failure retries (io/retry.py; docs/DURABILITY.md).

        ``primary`` may donate live buffers; ``retry_call`` must build its own
        input copies (it runs only after ``restore`` reinstalled the recovery
        snapshot, so the live state is valid again and retries can never
        double-donate). A fresh key's failure propagates raw (trace/compile
        problem — the sticky eager fallback upstream is correct); a warm
        failure exhausting its retry budget raises :class:`_DispatchFailure`
        wrapping the final error. A :class:`DispatchStallError` is never
        retried: re-running a call that just hung for its whole deadline would
        park the loop for another one.
        """
        from torchmetrics_tpu.io.retry import (
            RetryPolicy,
            backoff_delays,
            default_dispatch_deadline,
            default_dispatch_retries,
            stall_watchdog,
        )

        deadline = default_dispatch_deadline()

        def once(call: Callable[[], Any]) -> Any:
            with stall_watchdog(
                deadline, what=f"donated dispatch for {self._owner_name()}", status=self.stats_dict
            ):
                return call()

        try:
            return once(primary)
        except Exception as err:
            if fresh:
                raise  # trace/compile failure: live state was never at risk
            restore()
            self.stats["dispatch_failures"] += 1
            retries = default_dispatch_retries()
            if retries and not isinstance(err, DispatchStallError):
                for delay in backoff_delays(RetryPolicy(max_retries=retries)):
                    time.sleep(delay)
                    self.stats["dispatch_retries"] += 1
                    try:
                        return once(retry_call)
                    except DispatchStallError as stalled:
                        err = stalled
                        break
                    except Exception as again:
                        rank_zero_debug(
                            f"torchmetrics_tpu executor: retry dispatch for {self._owner_name()}"
                            f" failed again ({type(again).__name__}: {again})"
                        )
                        err = again
            raise _DispatchFailure(err)

    def _get_fn(self, key: Any, builder: Callable[[], Callable]) -> Tuple[Callable, bool]:
        fn = self._cache.get(key)
        if fn is not None:
            self.stats["cache_hits"] += 1
            return fn, False
        fn = jax.jit(builder(), donate_argnums=0)
        self._cache[key] = fn
        self.stats["compiles"] += 1
        return fn, True

    def stats_dict(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["disabled_reason"] = self.disabled_reason
        out["fallback_reason"] = self.disabled_reason
        out["bucketing_enabled"] = self._bucketing_ok
        out["cached_executables"] = len(self._cache)
        return out


class MetricExecutor(_ExecutorBase):
    """Per-``Metric`` executor: compiled update/forward with donated state."""

    def __init__(self, metric: Any, plain_functional: bool, plain_forward: bool) -> None:
        super().__init__()
        self._metric = metric
        self._plain_functional = plain_functional
        self._plain_forward = plain_forward

    def _owner_name(self) -> str:
        return type(self._metric).__name__

    # ------------------------------------------------------------ eligibility
    def _static_reason(self) -> Optional[str]:
        if self._static_reason_cached != ():
            return self._static_reason_cached
        m = self._metric
        reason = None
        if not self._plain_functional:
            reason = "functional_update/functional_compute overridden"
        elif getattr(m, "executor_compatible", True) is False:
            reason = "metric declares executor_compatible=False"
        elif not m._defaults:
            reason = "no registered states"
        elif any(isinstance(v, list) for v in m._defaults.values()):
            reason = "list states change pytree structure every update"
        elif m.compute_on_cpu:
            reason = "compute_on_cpu moves states host-side after update"
        elif getattr(m, "validate_args", None) is True:
            reason = "validate_args=True needs concrete input checks"
        else:
            hook = getattr(m, "_executor_traceable", None)
            if callable(hook) and not hook():
                reason = "metric declares itself untraceable"
        self._static_reason_cached = reason
        return reason

    def usable(self) -> bool:
        return self.disabled_reason is None and self._static_reason() is None

    def stats_dict(self) -> Dict[str, Any]:
        out = super().stats_dict()
        if out["disabled_reason"] is None:
            out["disabled_reason"] = self._static_reason()
        out["fallback_reason"] = out["disabled_reason"]
        return out

    def bucketable(self) -> bool:
        if not self._bucketing_ok:
            return False
        m = self._metric
        for field, fx in m._reductions.items():
            if fx not in _FUSABLE_REDUCTIONS:
                return False
            if fx == "sum" and jnp.asarray(m._defaults[field]).dtype == jnp.bool_:
                return False
        return True

    # --------------------------------------------------------------- builders
    def _consts(self):
        m = self._metric
        defaults = {k: jnp.asarray(v) for k, v in m._defaults.items()}
        return defaults

    def _build_update(self, treedef, batched, bucket, padded, bool_spec, n_leaves):
        m = self._metric
        defaults = self._consts()

        if not padded:
            def raw(state, *dyn):
                leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
                args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
                return m.functional_update(state, *args, **kwargs)
            return raw

        def raw(state, n_valid, *dyn):
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            g = m.functional_update(state, *args, **kwargs)
            r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            extra = jnp.asarray(bucket, jnp.int32) - n_valid
            return _subtract_pad_contribution(m, g, defaults, defaults, r_args, r_kwargs, extra)

        return raw

    def _build_forward(self, treedef, batched, bucket, padded, variant, bool_spec, n_leaves):
        m = self._metric
        defaults = self._consts()
        one = jnp.asarray(1, jnp.int32)

        def batch_state(leaves):
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            return m.functional_update(defaults, *args, **kwargs), (args, kwargs)

        def raw(state, count, *rest):
            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn = rest
                extra = None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            bs, (args, kwargs) = batch_state(leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
                bs = _subtract_pad_contribution(m, bs, defaults, defaults, r_args, r_kwargs, extra)
            value = m.functional_compute(bs)
            if variant == "reduce":
                new_state = m.merge_states(state, bs, counts=(count, one))
            else:
                new_state = m.functional_update(state, *args, **kwargs)
                if extra is not None:
                    r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
                    new_state = _subtract_pad_contribution(
                        m, new_state, defaults, defaults, r_args, r_kwargs, extra
                    )
            return new_state, value

        return raw

    # ----------------------------------------------------------------- shared
    def _prepare(self, args, kwargs):
        """Classify inputs; returns (treedef, leaves, sig, batched, bucket, n) or None.

        ``(args, kwargs)`` flatten as one pytree: dict keys live in the treedef
        (jax sorts them), so keyword order never splits the executable cache.
        """
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = _classify_leaves(leaves)
        if sig is None:
            return None
        n = _common_batch_dim(leaves)
        bucket = None
        padded = False
        if n is not None and n > 0 and self.bucketable():
            bucket = bucket_size(n)
            padded = bucket != n
        if padded:
            batched = tuple(
                _is_concrete_array(l) and getattr(l, "ndim", 0) >= 1 and int(l.shape[0]) == n
                for l in leaves
            )
            call_leaves = _pad_leaves(leaves, batched, bucket)
            sig = _classify_leaves(call_leaves)
        else:
            batched = None
            call_leaves = list(leaves)
        dyn_leaves, bool_spec = _split_static_bools(call_leaves)
        return treedef, dyn_leaves, sig, batched, bucket, n, padded, bool_spec, len(call_leaves)

    # ------------------------------------------------------------------ entry
    def run_update(self, args: tuple, kwargs: dict) -> bool:
        """Execute ``update`` through the compiled path; False -> caller falls
        back to the eager body (never partially applied).

        Failure containment (docs/EXECUTOR.md "Failure semantics"): a FRESH
        key's failure is a trace/compile problem — inputs were copies, so the
        sticky eager fallback is safe. A WARM executable's failure is a
        runtime/dispatch problem after the inputs may have been donated: the
        live state has been restored from the recovery snapshot and the
        original error propagates (no silent eager re-run of the batch)."""
        if not self.usable():
            return False
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False
        try:
            return self._run_update(args, kwargs)
        except _DispatchFailure as df:
            raise df.original
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:  # sticky: a metric that cannot trace stays eager
            self._disable(f"{type(err).__name__}: {err}")
            return False

    def _run_update(self, args, kwargs) -> bool:
        prep = self._prepare(args, kwargs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        m = self._metric

        key = ("u", treedef, sig, batched, bucket if padded else None)
        fn, fresh = self._get_fn(
            key, lambda: self._build_update(treedef, batched, bucket, padded, bool_spec, n_leaves)
        )

        state = {k: m._state[k] for k in m._defaults}
        need_copy = fresh or m._state_escaped or m._state_shared
        state_in = _tree_copy(state) if need_copy else state
        # donation in play -> keep a host-side recovery reference (ISSUE 2)
        recovery = None if need_copy else self._snapshot(state)

        do_probe = padded and not self._pad_validated
        oracle = m.functional_update(state, *args, **kwargs) if do_probe else None

        def call_fn(state_arg):
            if padded:
                return fn(state_arg, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(state_arg, *call_leaves)

        # profiler span naming the metric so wall time attributes to it
        # (ISSUE 3 observability; the traced body carries matching
        # jax.named_scope annotations via functional_update)
        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{self._owner_name()}"):
            new_state = self._guarded_dispatch(
                lambda: call_fn(state_in),
                lambda: call_fn(_tree_copy({k: m._state[k] for k in m._defaults})),
                fresh,
                lambda: self._restore(m, recovery) if not need_copy else None,
            )
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            if _states_close(new_state, oracle, m._defaults):
                self._pad_validated = True
            else:
                # bucketing is numerically unsafe for this metric: discard the
                # padded result (the live state was untouched — probe calls
                # always run on a copy) and re-dispatch through the
                # exact-shape compiled path, so every call stays consistently
                # compiled rather than one call carrying eager-flavoured
                # rounding
                self._bucketing_ok = False
                return self._run_update(args, kwargs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if need_copy else "donated_calls"] += 1
        object.__setattr__(m, "_state", dict(new_state))
        m.__dict__["_state_escaped"] = False
        # the wrapper bumped _update_count before this call, so the pre-call
        # recovery snapshot describes exactly count-1 committed updates — the
        # Autosaver reuses it as a free (already host-side) checkpoint source
        self._last_recovery = None if recovery is None else (int(m._update_count) - 1, recovery)
        return True

    def run_forward(self, args: tuple, kwargs: dict) -> Tuple[bool, Any]:
        """Execute ``forward`` as one fused ``(state, batch) -> (state', value)``
        computation. Returns ``(handled, batch_value)``."""
        m = self._metric
        if not self.usable() or not self._plain_forward or m.dist_sync_on_step:
            return False, None
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False, None
        try:
            return self._run_forward(args, kwargs)
        except _DispatchFailure as df:
            raise df.original
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return False, None

    def _forward_oracle(self, variant, state, args, kwargs, count):
        m = self._metric
        bs = m.functional_update(m.functional_init(), *args, **kwargs)
        value = m.functional_compute(bs)
        if variant == "reduce":
            new_state = m.merge_states(state, bs, counts=(count, 1))
        else:
            new_state = m.functional_update(state, *args, **kwargs)
        return new_state, value

    def _run_forward(self, args, kwargs):
        prep = self._prepare(args, kwargs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False, None
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        m = self._metric
        variant = "reduce" if m.full_state_update is False else "full"

        key = ("f", variant, treedef, sig, batched, bucket if padded else None)
        fn, fresh = self._get_fn(
            key,
            lambda: self._build_forward(treedef, batched, bucket, padded, variant, bool_spec, n_leaves),
        )

        state = {k: m._state[k] for k in m._defaults}
        count = int(m._update_count)
        need_copy = fresh or m._state_escaped or m._state_shared
        state_in = _tree_copy(state) if need_copy else state
        recovery = None if need_copy else self._snapshot(state)

        do_probe = padded and not self._pad_validated
        oracle = self._forward_oracle(variant, state, args, kwargs, count) if do_probe else None

        count_arr = jnp.asarray(count, jnp.int32)

        def call_fn(state_arg):
            if padded:
                return fn(state_arg, count_arr, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(state_arg, count_arr, *call_leaves)

        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{self._owner_name()}"):
            new_state, value = self._guarded_dispatch(
                lambda: call_fn(state_in),
                lambda: call_fn(_tree_copy({k: m._state[k] for k in m._defaults})),
                fresh,
                lambda: self._restore(m, recovery) if not need_copy else None,
            )
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            if _states_close(new_state, oracle[0], m._defaults) and _values_close(value, oracle[1]):
                self._pad_validated = True
            else:
                # see _run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_forward(args, kwargs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if need_copy else "donated_calls"] += 1
        object.__setattr__(m, "_state", dict(new_state))
        m.__dict__["_state_escaped"] = False
        m._update_count += 1
        m._computed = None
        m._to_sync = m.sync_on_compute
        m._should_unsync = True
        # snapshot taken pre-bump: it describes count-1 committed updates
        self._last_recovery = None if recovery is None else (int(m._update_count) - 1, recovery)
        return True, value


class CollectionExecutor(_ExecutorBase):
    """Fused executor for a ``MetricCollection``: one compiled call updates (or
    forwards) EVERY compute group, with the combined leader-state pytree
    donated. Engages only when every group leader is executor-eligible;
    otherwise the collection falls back to the per-metric loop (where each
    leader still uses its own :class:`MetricExecutor`)."""

    def __init__(self, collection: Any) -> None:
        super().__init__()
        self._coll = collection

    def _owner_name(self) -> str:
        return f"MetricCollection[{', '.join(self._coll._modules)}]"

    def _cache_collection_recovery(self, donated, leader_execs) -> None:
        """Keep the step's per-group recovery snapshots for Autosaver reuse —
        only when EVERY group donated (and so has one); a partial set cannot
        describe a consistent collection-wide checkpoint."""
        if len(donated) == len(leader_execs) and all(snap is not None for *_, snap in donated):
            # _install already bumped each leader: snapshots describe count-1
            self._last_recovery = {
                name: (int(self._coll._modules[name]._update_count) - 1, snap)
                for name, _, _, snap in donated
            }
        else:
            self._last_recovery = None

    def _restore_groups(self, donated) -> None:
        """Reinstall recovery snapshots for every donated group after a failed
        fused dispatch, re-pointing followers at the leader's restored arrays."""
        mods = self._coll._modules
        for name, m, cg, recovery in donated:
            self._restore(m, recovery)
            for member in cg[1:]:
                follower = mods[member]
                for field in m._defaults:
                    follower._state[field] = m._state[field]
                follower.__dict__["_state_escaped"] = True

    # ------------------------------------------------------------ eligibility
    def _leaders(self):
        coll = self._coll
        return [(cg[0], coll._modules[cg[0]], cg) for cg in coll._groups.values()]

    def _leader_executors(self):
        out = []
        for name, m, cg in self._leaders():
            ex = m._get_executor()
            if ex is None or not ex.usable():
                return None
            if any(getattr(mm, "_executor_enabled", None) is False for mm in (self._coll._modules[x] for x in cg)):
                return None
            out.append((name, m, cg, ex))
        return out

    def bucketable(self, leader_execs) -> bool:
        return self._bucketing_ok and all(ex.bucketable() for _, _, _, ex in leader_execs)

    def _kwarg_names(self, m, kwargs) -> Tuple[str, ...]:
        return tuple(sorted(m._filter_kwargs(**kwargs)))

    # --------------------------------------------------------------- builders
    def _build_update(self, treedef, batched, bucket, padded, leader_specs, bool_spec, n_leaves):
        coll = self._coll

        def raw(states, *rest):
            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn, extra = rest, None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            out = {}
            for leader, kw_names, defaults in leader_specs:
                m = coll._modules[leader]
                fkw = {k: kwargs[k] for k in kw_names}
                g = m.functional_update(states[leader], *args, **fkw)
                if extra is not None:
                    rkw = {k: r_kwargs[k] for k in kw_names}
                    g = _subtract_pad_contribution(m, g, defaults, defaults, r_args, rkw, extra)
                out[leader] = g
            return out

        return raw

    def _build_forward(self, treedef, batched, bucket, padded, leader_specs, bool_spec, n_leaves):
        coll = self._coll
        one = jnp.asarray(1, jnp.int32)

        def raw(states, counts, *rest):
            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn, extra = rest, None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            new_states, values = {}, {}
            for leader, members, kw_names, defaults in leader_specs:
                m = coll._modules[leader]
                fkw = {k: kwargs[k] for k in kw_names}
                bs = m.functional_update(defaults, *args, **fkw)
                if extra is not None:
                    rkw = {k: r_kwargs[k] for k in kw_names}
                    bs = _subtract_pad_contribution(m, bs, defaults, defaults, r_args, rkw, extra)
                new_states[leader] = m.merge_states(states[leader], bs, counts=(counts[leader], one))
                for name in members:
                    values[name] = coll._modules[name].functional_compute(bs)
            return new_states, values

        return raw

    # ----------------------------------------------------------------- shared
    def _prepare(self, args, kwargs, leader_execs):
        leaves, treedef = jax.tree_util.tree_flatten((args, tuple(sorted(kwargs.items()))))
        sig = _classify_leaves(leaves)
        if sig is None:
            return None
        n = _common_batch_dim(leaves)
        bucket, padded = None, False
        if n is not None and n > 0 and self.bucketable(leader_execs):
            bucket = bucket_size(n)
            padded = bucket != n
        if padded:
            batched = tuple(
                _is_concrete_array(l) and getattr(l, "ndim", 0) >= 1 and int(l.shape[0]) == n
                for l in leaves
            )
            call_leaves = _pad_leaves(leaves, batched, bucket)
            sig = _classify_leaves(call_leaves)
        else:
            batched = None
            call_leaves = list(leaves)
        dyn_leaves, bool_spec = _split_static_bools(call_leaves)
        return treedef, dyn_leaves, sig, batched, bucket, n, padded, bool_spec, len(call_leaves)

    def _group_need_copy(self, cg, fresh) -> bool:
        mods = self._coll._modules
        return fresh or any(mods[name]._state_escaped for name in cg)

    def _install(self, leader, new_state, cg, bump_count: bool) -> None:
        mods = self._coll._modules
        m0 = mods[leader]
        object.__setattr__(m0, "_state", dict(new_state))
        if bump_count:
            m0._update_count += 1
            m0._mark_unreduced()  # fresh local accumulation under reduce="deferred"
        m0._computed = None
        for name in cg:
            mm = mods[name]
            mm.__dict__["_state_escaped"] = False
            mm.__dict__["_state_shared"] = True

    # ------------------------------------------------------------------ entry
    def run_update(self, args: tuple, kwargs: dict) -> bool:
        if self.disabled_reason is not None:
            return False
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False
        leader_execs = self._leader_executors()
        if leader_execs is None:
            return False
        try:
            return self._run_update(args, kwargs, leader_execs)
        except _DispatchFailure as df:
            raise df.original
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return False

    def _run_update(self, args, kwargs, leader_execs) -> bool:
        prep = self._prepare(args, kwargs, leader_execs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        coll = self._coll

        kw_map = tuple((name, self._kwarg_names(m, kwargs)) for name, m, _ in self._leaders())
        key = ("u", treedef, sig, batched, bucket if padded else None, kw_map)

        def builder():
            specs = [
                (name, dict(kw_map)[name], {k: jnp.asarray(v) for k, v in m._defaults.items()})
                for name, m, _ in self._leaders()
            ]
            return self._build_update(treedef, batched, bucket, padded, specs, bool_spec, n_leaves)

        fn, fresh = self._get_fn(key, builder)

        states, copied = {}, False
        donated = []  # groups whose live buffers go into the donated call
        for name, m, cg, _ in leader_execs:
            st = {k: m._state[k] for k in m._defaults}
            if self._group_need_copy(cg, fresh):
                st = _tree_copy(st)
                copied = True
            else:
                donated.append((name, m, cg, self._snapshot(st)))
            states[name] = st

        do_probe = padded and not self._pad_validated
        oracle = None
        if do_probe:
            oracle = {
                name: m.functional_update({k: m._state[k] for k in m._defaults}, *args, **m._filter_kwargs(**kwargs))
                for name, m, _, _ in leader_execs
            }

        def call_fn(states_arg):
            if padded:
                return fn(states_arg, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(states_arg, *call_leaves)

        def copied_states():
            return {
                name: _tree_copy({k: m._state[k] for k in m._defaults})
                for name, m, _, _ in leader_execs
            }

        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{self._owner_name()}"):
            new_states = self._guarded_dispatch(
                lambda: call_fn(states),
                lambda: call_fn(copied_states()),
                fresh,
                lambda: self._restore_groups(donated),
            )
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            ok = all(
                _states_close(new_states[name], oracle[name], m._defaults)
                for name, m, _, _ in leader_execs
            )
            if ok:
                self._pad_validated = True
            else:
                # see MetricExecutor._run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_update(args, kwargs, leader_execs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if copied else "donated_calls"] += 1
        for name, _, cg, _ in leader_execs:
            self._install(name, new_states[name], cg, bump_count=True)
        self._cache_collection_recovery(donated, leader_execs)
        return True

    def run_forward(self, args: tuple, kwargs: dict) -> Optional[Dict[str, Any]]:
        """Fused forward for the WHOLE collection, or None to fall back.

        Only engages when every group qualifies for the reduce-merge forward
        (all members ``full_state_update=False``, no ``dist_sync_on_step``)."""
        if self.disabled_reason is not None:
            return None
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return None
        leader_execs = self._leader_executors()
        if leader_execs is None:
            return None
        from torchmetrics_tpu.metric import Metric  # deferred: avoids import cycle

        coll = self._coll
        for name, m0, cg, ex in leader_execs:
            if not ex._plain_forward:
                return None
            for member in cg:
                mm = coll._modules[member]
                if mm.full_state_update is not False or mm.dist_sync_on_step:
                    return None
                # every member's compute traces inside the fused call
                if type(mm).functional_compute is not Metric.functional_compute:
                    return None
        try:
            return self._run_forward(args, kwargs, leader_execs)
        except _DispatchFailure as df:
            raise df.original
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return None

    def _run_forward(self, args, kwargs, leader_execs):
        prep = self._prepare(args, kwargs, leader_execs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return None
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        coll = self._coll

        kw_map = tuple((name, self._kwarg_names(m, kwargs)) for name, m, _ in self._leaders())
        key = ("f", treedef, sig, batched, bucket if padded else None, kw_map)

        def builder():
            specs = [
                (
                    name,
                    tuple(cg),
                    dict(kw_map)[name],
                    {k: jnp.asarray(v) for k, v in m._defaults.items()},
                )
                for name, m, cg in self._leaders()
            ]
            return self._build_forward(treedef, batched, bucket, padded, specs, bool_spec, n_leaves)

        fn, fresh = self._get_fn(key, builder)

        states, copied = {}, False
        donated = []  # groups whose live buffers go into the donated call
        counts = {}
        for name, m, cg, _ in leader_execs:
            st = {k: m._state[k] for k in m._defaults}
            if self._group_need_copy(cg, fresh):
                st = _tree_copy(st)
                copied = True
            else:
                donated.append((name, m, cg, self._snapshot(st)))
            states[name] = st
            counts[name] = jnp.asarray(int(m._update_count), jnp.int32)

        do_probe = padded and not self._pad_validated
        oracle = None
        if do_probe:
            oracle_states, oracle_values = {}, {}
            for name, m, cg, _ in leader_execs:
                bs = m.functional_update(m.functional_init(), *args, **m._filter_kwargs(**kwargs))
                oracle_states[name] = m.merge_states(
                    {k: m._state[k] for k in m._defaults}, bs, counts=(int(m._update_count), 1)
                )
                for member in cg:
                    oracle_values[member] = coll._modules[member].functional_compute(bs)
            oracle = (oracle_states, oracle_values)

        def call_fn(states_arg):
            if padded:
                return fn(states_arg, counts, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(states_arg, counts, *call_leaves)

        def copied_states():
            return {
                name: _tree_copy({k: m._state[k] for k in m._defaults})
                for name, m, _, _ in leader_execs
            }

        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{self._owner_name()}"):
            new_states, values = self._guarded_dispatch(
                lambda: call_fn(states),
                lambda: call_fn(copied_states()),
                fresh,
                lambda: self._restore_groups(donated),
            )
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            ok = all(
                _states_close(new_states[name], oracle[0][name], m._defaults)
                for name, m, _, _ in leader_execs
            ) and _values_close(values, oracle[1])
            if ok:
                self._pad_validated = True
            else:
                # see MetricExecutor._run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_forward(args, kwargs, leader_execs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if copied else "donated_calls"] += 1
        for name, _, cg, _ in leader_execs:
            self._install(name, new_states[name], cg, bump_count=True)
        self._cache_collection_recovery(donated, leader_execs)
        return dict(values)


# ---------------------------------------------------------------------------
# synced-path fusion: update -> sync -> compute as ONE computation
# ---------------------------------------------------------------------------

def make_value_packer(example_values: Any):
    """Build (pack, unpack) for a fixed values pytree.

    ``pack`` (trace-safe) concatenates all leaves of a values pytree into one
    flat vector per dtype — an N-metric collection then materialises O(dtypes)
    replicated output buffers per step instead of O(N). ``unpack`` (host-side)
    restores the original pytree from the packed dict.
    """
    leaves, treedef = jax.tree_util.tree_flatten(example_values)
    specs = [(tuple(l.shape), jnp.dtype(l.dtype)) for l in leaves]
    order: Dict[str, List[int]] = {}
    for i, (_, dt) in enumerate(specs):
        order.setdefault(str(dt), []).append(i)

    def pack(tree):
        lv = jax.tree_util.tree_leaves(tree)
        return {
            dt: jnp.concatenate([jnp.ravel(lv[i]) for i in idxs])
            for dt, idxs in order.items()
        }

    def unpack(packed):
        out: List[Any] = [None] * len(specs)
        for dt, idxs in order.items():
            flat = np.asarray(packed[dt])
            off = 0
            for i in idxs:
                shape, _ = specs[i]
                size = int(np.prod(shape)) if shape else 1
                out[i] = flat[off:off + size].reshape(shape)
                off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return pack, unpack


def make_synced_collection_step(
    collection: Any, axis_name: str = "batch", pack_values: bool = True, reduce: str = "step"
):
    """Fused ``(states, *batch) -> (states', packed_values)`` synced step.

    Meant to be wrapped in the caller's ``shard_map``/``jit`` over a mesh
    binding ``axis_name``. One computation runs every compute group's update,
    folds the whole collection's sync collectives into one ``psum`` per
    (reduction, dtype) (via ``MetricCollection.functional_sync``'s cross-group
    leaf fusion), computes every metric from the synced state, and packs the
    computed leaves per dtype. Returns ``(step, unpack)`` where ``unpack``
    (host-side) restores the values dict from the packed output; it is built
    lazily on the first call's structure when ``pack_values`` is True.

    With ``reduce="deferred"`` the per-step collectives disappear entirely and
    the return becomes ``(local_step, reduce_step, unpack)``: ``local_step``
    accumulates into *sharded* state (leading shard axis, spec
    ``collection.sharded_state_spec(axis_name)``) with ZERO collectives, and
    ``reduce_step(states) -> packed_values`` applies every declared
    ``dist_reduce_fx`` exactly once — the read point of the deferred policy
    (docs/SHARDING.md). :func:`make_deferred_collection_step` wraps the pair
    in ``shard_map``/``jit`` (donation intact) for you.
    """
    if reduce == "deferred":
        return _make_deferred_bodies(collection, axis_name, pack_values)
    if reduce != "step":
        raise ValueError(f"reduce must be 'step' or 'deferred', got {reduce!r}")
    box: Dict[str, Any] = {}

    def step(states, *args, **kwargs):
        st = collection.functional_update(states, *args, **kwargs)
        synced = collection.functional_sync(st, axis_name)
        values = collection.functional_compute(synced)
        if pack_values:
            if "pack" not in box:
                box["pack"], box["unpack"] = make_value_packer(values)
            values = box["pack"](values)
        return st, values

    def unpack(packed):
        if not pack_values:
            return packed
        return box["unpack"](packed)

    return step, unpack


def _make_deferred_bodies(collection: Any, axis_name: str, pack_values: bool):
    """(local_step, reduce_step, unpack) raw bodies for the deferred policy;
    both are meant to run inside the caller's ``shard_map`` with the state
    spec from ``collection.sharded_state_spec(axis_name)``."""
    from torchmetrics_tpu.parallel.sync import reshard_local_state, unshard_local_state

    box: Dict[str, Any] = {}

    def local_step(states, *args, **kwargs):
        # purely local accumulation: unshard -> update -> reshard, no collectives
        with jax.named_scope("tm_tpu.update"):
            local = collection.functional_update(unshard_local_state(states), *args, **kwargs)
        return reshard_local_state(local)

    def reduce_step(states):
        # the single deferred rendezvous: one fused collective per
        # (reduction, dtype) for the whole collection, then compute
        synced = collection.reduce_sharded_states(states, axis_name)
        values = collection.functional_compute(synced)
        if pack_values:
            if "pack" not in box:
                box["pack"], box["unpack"] = make_value_packer(values)
            values = box["pack"](values)
        return values

    def unpack(packed):
        if not pack_values:
            return packed
        return box["unpack"](packed)

    return local_step, reduce_step, unpack


class DeferredCollectionStep:
    """Compiled deferred-reduction drivers for one collection on one mesh
    (built by :func:`make_deferred_collection_step`; see docs/SHARDING.md).

    State lives *sharded per-device* along the mesh data axis; the hot loop
    pays zero collectives, and every declared ``dist_reduce_fx`` runs exactly
    once at the read point:

    - :meth:`init_states` — fresh sharded states placed on the mesh.
    - :meth:`local_step` — ``(states, *batch) -> states'``: ONE compiled
      dispatch of purely local accumulation, state pytree **donated**.
    - :meth:`local_epoch` — ``(states, *stacked) -> states'``: a whole chunk
      of steps (leading axis = steps) folded into ONE dispatch via
      ``lax.scan``. Because no step carries a rendezvous, devices run the
      entire chunk decoupled — this is the MapReduce shape (DrJAX) that makes
      epoch-style eval loops run at unsynced speed.
    - :meth:`reduce` — ``states -> values``: the separately cached read-point
      executable; one fused collective per (reduction, dtype) for the whole
      collection, then every metric's compute.
    """

    def __init__(self, collection: Any, mesh: Any, axis_name: str, pack_values: bool, batch_specs: Any, donate: bool) -> None:
        self._coll = collection
        self._mesh = mesh
        self._axis = axis_name
        self._batch_specs = batch_specs
        self._donate = donate
        self._local_body, self._reduce_body, self._unpack = _make_deferred_bodies(
            collection, axis_name, pack_values
        )
        self._state_spec = collection.sharded_state_spec(axis_name)
        self._compiled: Dict[Any, Callable] = {}

    def _b_specs(self, batch):
        from jax.sharding import PartitionSpec as P

        if self._batch_specs is not None:
            return tuple(self._batch_specs)
        return tuple(P(self._axis) for _ in batch)

    def _epoch_specs(self, batch):
        # stacked chunk: leading axis is steps (unsharded), batch dim next
        from jax.sharding import PartitionSpec as P

        if self._batch_specs is not None:
            return tuple(P(None, *sp) for sp in self._batch_specs)
        return tuple(P(None, self._axis) for _ in batch)

    def init_states(self):
        from jax.sharding import NamedSharding

        states = self._coll.init_sharded_states(len(self._mesh.devices.flatten()))
        shardings = jax.tree_util.tree_map(lambda sp: NamedSharding(self._mesh, sp), self._state_spec)
        return jax.device_put(states, shardings)

    def _get(self, key, builder):
        fn = self._compiled.get(key)
        if fn is None:
            fn = builder()
            self._compiled[key] = fn
        return fn

    def local_step(self, states, *batch):
        from torchmetrics_tpu.parallel.sync import shard_map_compat

        def build():
            mapped = shard_map_compat(
                self._local_body, self._mesh, (self._state_spec,) + self._b_specs(batch), self._state_spec
            )
            return jax.jit(mapped, donate_argnums=0) if self._donate else jax.jit(mapped)

        fn = self._get(("local", len(batch)), build)
        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{type(self._coll).__name__}"):
            return fn(states, *batch)

    def local_epoch(self, states, *stacked):
        from torchmetrics_tpu.parallel.sync import shard_map_compat, reshard_local_state, unshard_local_state

        def build():
            def epoch_body(st, *chunk):
                local = unshard_local_state(st)

                def one(carry, batch):
                    return self._coll.functional_update(carry, *batch), None

                with jax.named_scope("tm_tpu.update"):
                    out, _ = jax.lax.scan(one, local, tuple(chunk))
                return reshard_local_state(out)

            mapped = shard_map_compat(
                epoch_body, self._mesh, (self._state_spec,) + self._epoch_specs(stacked), self._state_spec
            )
            return jax.jit(mapped, donate_argnums=0) if self._donate else jax.jit(mapped)

        fn = self._get(("epoch", len(stacked)), build)
        with jax.profiler.TraceAnnotation(f"tm_tpu.dispatch/{type(self._coll).__name__}"):
            return fn(states, *stacked)

    def reduce(self, states):
        from jax.sharding import PartitionSpec as P

        from torchmetrics_tpu.parallel.sync import shard_map_compat

        def build():
            # values are replicated after the fused collectives; out_specs=P()
            return jax.jit(shard_map_compat(self._reduce_body, self._mesh, (self._state_spec,), P()))

        fn = self._get("reduce", build)
        with jax.profiler.TraceAnnotation("tm_tpu.reduce"):
            return self._unpack(fn(states))


def make_deferred_collection_step(
    collection: Any,
    mesh: Any,
    axis_name: str = "batch",
    pack_values: bool = True,
    batch_specs: Any = None,
    donate: bool = True,
) -> DeferredCollectionStep:
    """Compile the deferred-reduction epoch loop for ``collection`` on ``mesh``.

    Returns a :class:`DeferredCollectionStep` whose ``local_step`` (one batch
    per dispatch) and ``local_epoch`` (a stacked chunk of steps per dispatch,
    scanned) accumulate into sharded state with ZERO per-step collectives and
    the state pytree donated; ``reduce`` applies every declared
    ``dist_reduce_fx`` exactly once (one fused rendezvous per
    (reduction, dtype) for the whole collection) — call it at
    compute()/epoch end.

    ``batch_specs`` gives the PartitionSpec(s) of the per-batch arguments
    (default: every argument sharded along ``axis_name`` on its leading dim).
    """
    return DeferredCollectionStep(collection, mesh, axis_name, pack_values, batch_specs, donate)


def latest_recovery_snapshot(obj: Any) -> Optional[Tuple[int, Dict[str, Any]]]:
    """The most recent donating dispatch's host-side recovery snapshot, shaped
    like a ``state()`` export — the Autosaver's free checkpoint source
    (io/checkpoint.py: the forced copy already exists; serializing it costs
    zero extra device sync).

    Returns ``(update_count, export)`` where the export carries the reserved
    ``"_update_count"`` key(s) like a real ``state()`` export, or None when no
    snapshot exists or it is STALE — i.e. not exactly one committed update
    behind the live state (state escaped, eager fallback engaged, recovery
    disabled): a stale snapshot would silently checkpoint old history.
    """
    ex = getattr(obj, "_executor_obj", None)
    rec = getattr(ex, "_last_recovery", None)
    if rec is None:
        return None
    if isinstance(ex, CollectionExecutor):
        coll = ex._coll
        export: Dict[str, Any] = {}
        counts = []
        for leader, (count, snap) in rec.items():
            if int(coll._modules[leader]._update_count) != count + 1:
                return None
            entry = dict(snap)
            entry[STATE_COUNT_KEY] = int(count)
            export[leader] = entry
            counts.append(int(count))
        if not counts:
            return None
        return max(counts), export
    count, snap = rec
    if int(ex._metric._update_count) != count + 1:
        return None
    export = dict(snap)
    export[STATE_COUNT_KEY] = int(count)
    return int(count), export


def executor_stats(obj: Any) -> Dict[str, Any]:
    """Executor instrumentation for a ``Metric`` or ``MetricCollection``.

    Returns zeroed stats when the executor has not engaged yet (or is
    disabled); see the keys in this module's ``_new_stats``.
    """
    ex = getattr(obj, "_executor_obj", None)
    if ex is None:
        out = _new_stats()
        out["disabled_reason"] = None
        out["fallback_reason"] = None
        out["bucketing_enabled"] = True
        out["cached_executables"] = 0
        return out
    return ex.stats_dict()
