"""Donated-state jitted executor for the eager stateful API (L2/L4).

The pure functional path (``functional_update`` inside a user's jitted train
step) has always enjoyed fused XLA execution; the stateful shell
(``Metric.update()``, ``forward()``, ``MetricCollection`` in a plain eval loop)
dispatched op-by-op from Python. This module closes that gap: every eager
``update``/``forward`` call looks up (or builds) a compiled function

    state' = f(state, *batch)

keyed by ``(call kind, input pytree structure, shape bucket, dtypes)`` with the
state pytree **donated** (``donate_argnums=0``), so large accumulators
(capacity-buffered curves, confusion matrices, feature buffers) are updated in
place instead of copied every step.

Shape bucketing
    Ragged batches (the last batch of an epoch) are padded up a small geometric
    ladder of power-of-two buckets so they reuse the warm executable instead of
    triggering a recompile. Padding rows are copies of the batch's first row;
    inside the trace the padded contribution is subtracted exactly for
    ``"sum"``-reduced states (duplicated real rows are no-ops for ``max``/
    ``min`` states). The correction assumes the update is per-sample additive,
    which the executor *verifies empirically*: the first padded call for a
    metric also runs the eager op-by-op oracle and compares; on any mismatch
    bucketing is disabled for that instance (exact-shape compilation remains).

Donation ownership
    Donating a buffer invalidates every other reference to it, so the executor
    only donates arrays it itself produced and that have not escaped to user
    code since. ``Metric`` tracks two flags:

    - ``_state_escaped`` — some state array may be referenced outside the
      metric (a ``state()`` export, an attribute read, a fresh ``reset`` whose
      arrays alias ``_defaults``). The next executor call copies the state
      once, then re-owns the result.
    - ``_state_shared`` — the arrays are aliased *by design* inside a
      ``MetricCollection`` compute group. The single-metric executor never
      donates shared state; the collection's fused executor manages the group
      as a whole.

    The first call on a fresh cache key also copies, so a compile-time failure
    can never consume live state.

Escape hatch
    ``Metric(..., executor=False)`` / ``MetricCollection(..., executor=False)``
    or the environment variable ``TORCHMETRICS_TPU_EXECUTOR=0`` restore the
    previous eager op-by-op path exactly; any error while tracing a metric's
    update falls back to the eager path permanently for that instance (the
    reason is recorded in :func:`executor_stats`).

Compile-ahead (ops/compile_cache.py; docs/EXECUTOR.md "Compile-ahead")
    Cold keys are the tail latency of fresh processes, so the executor layers
    a cross-process cache over its in-memory one: fresh compiles are exported
    (``jax.export``) and atomically persisted in the background, a later
    process's miss loads the serialized computation from disk instead of
    re-tracing (``disk_hits``), and — with background compilation enabled —
    a cold key dispatches the step through the eager op-by-op body while the
    compile runs on the shared worker, the warm executable swapping in
    atomically for the next call (``eager_misses``/``background_compiles``).
    :meth:`~_ExecutorBase.warmup` precompiles the bucket ladder ahead of
    traffic, and every executor records a replayable shape profile
    (:meth:`~_ExecutorBase.shape_profile`) so ``warmup_from_manifest`` can
    rebuild exactly the buckets a previous run actually saw.

Synced path
    :func:`make_synced_collection_step` builds the fused
    ``update -> sync -> compute`` step used under ``shard_map``: the
    collection-level leaf fusion in ``parallel/sync.py`` coalesces the whole
    collection's collectives into one ``psum`` per (reduction, dtype) per step,
    and computed values are packed into one replicated buffer per dtype so an
    N-metric collection pays O(dtypes), not O(N), per-output dispatch cost.
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.ops import compile_cache
from torchmetrics_tpu.utils.exceptions import DispatchStallError
from torchmetrics_tpu.utils.prints import rank_zero_debug, rank_zero_warn

# CPU (and some other) backends do not implement buffer donation; jax warns on
# every dispatch. Donation is still semantically correct there (silently
# ignored), so silence exactly that message.
warnings.filterwarnings("ignore", message="Some donated buffers were not usable")

ENV_FLAG = "TORCHMETRICS_TPU_EXECUTOR"
#: set to "0" to drop the per-call host-side recovery snapshot taken before a
#: donating dispatch (docs/EXECUTOR.md "Failure semantics") — faster steady
#: state, but a failed dispatch then resets the metric instead of restoring it
RECOVERY_ENV_FLAG = "TORCHMETRICS_TPU_EXECUTOR_RECOVERY"

#: reserved key carried by ``Metric.state()`` exports (see metric.py)
STATE_COUNT_KEY = "_update_count"

#: reserved key marking a stacked sharded export (mirrors Metric._STATE_SHARDS_KEY)
STATE_SHARDS_KEY = "_sharded_shards"

_BUCKET_FLOOR = 8
_FUSABLE_REDUCTIONS = ("sum", "max", "min")
_PY_SCALARS = (bool, int, float, complex, np.generic)


def _ingest_notify(new_state: Any) -> None:
    """The executor half of the slab-aware dispatch seam: hand the committed
    state to ops/ingest.py so an armed staging slab picks up its strong
    retire token (a no-op thread-local read outside a lane-router round)."""
    from torchmetrics_tpu.ops import ingest

    ingest.notify_dispatched(new_state)


def executor_enabled_default() -> bool:
    """Global default from the environment (``TORCHMETRICS_TPU_EXECUTOR``)."""
    return os.environ.get(ENV_FLAG, "1").strip().lower() not in ("0", "false", "off", "no")


def recovery_enabled_default() -> bool:
    """Whether donating calls keep a host-side recovery snapshot
    (``TORCHMETRICS_TPU_EXECUTOR_RECOVERY``, on by default)."""
    return os.environ.get(RECOVERY_ENV_FLAG, "1").strip().lower() not in ("0", "false", "off", "no")


class _DispatchFailure(Exception):
    """Internal: a WARM executable failed at dispatch time.

    By then the inputs may already have been donated, so the executor has
    restored the live state (from the host-side recovery snapshot) before
    raising this; the outer entry point unwraps and propagates ``original`` to
    the caller instead of falling back to the eager body — the eager body
    would silently re-run the batch and turn an error into a double-count
    hazard, and a transient runtime failure must not permanently disable the
    compiled path the way a trace failure does.
    """

    def __init__(self, original: BaseException) -> None:
        super().__init__(str(original))
        self.original = original


class _DiskEntryFailure(Exception):
    """Internal: a disk-loaded executable failed on its FIRST dispatch.

    Persisted entries always dispatch with copied (fresh-key) inputs, so the
    live state was never at risk — but sticky-disabling the executor (the
    trace-failure response) would be wrong: without the disk layer this key
    would have compiled fine. The entry points catch this, evict the entry
    from memory and disk, and retry the call through a fresh inline compile.
    """

    def __init__(self, key: Any, key_desc: str, original: BaseException) -> None:
        super().__init__(str(original))
        self.key = key
        self.key_desc = key_desc
        self.original = original


class _PersistSpec:
    """Everything a background compile/persist job may touch: the key's
    stable cross-process description, export avals, a factory producing
    fresh zero-filled dummy arguments, and a builder bound to a DETACHED
    deep copy of the owner — never the live metric. ``functional_update``
    swaps ``self._state`` while tracing, so tracing the live object off the
    main thread would race every concurrent update; jobs trace a clone whose
    computation is identical (same code, same defaults) but whose state
    nobody else touches."""

    __slots__ = ("key_desc", "avals", "dummy_args", "make_clone_builder")

    def __init__(
        self,
        key_desc: str,
        avals: Tuple[Any, ...],
        dummy_args: Callable[[], Tuple[Any, ...]],
        make_clone_builder: Callable[[], Callable[[], Callable]],
    ) -> None:
        self.key_desc = key_desc
        self.avals = avals
        self.dummy_args = dummy_args
        self.make_clone_builder = make_clone_builder


def _stable_key_repr(obj: Any) -> str:
    """Deterministic cross-process rendering of an in-memory cache key
    (treedefs stringify; primitives repr)."""
    if isinstance(obj, tuple):
        return "(" + ",".join(_stable_key_repr(o) for o in obj) + ")"
    if hasattr(obj, "num_leaves") and type(obj).__name__ == "PyTreeDef":
        return str(obj)
    return repr(obj)


def _aval_of(x: Any) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(np.shape(x)), jnp.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype)


def _zeros_like_spec(shapes_dtypes: Sequence[Tuple[tuple, Any]]) -> List[Any]:
    return [jnp.zeros(shape, dtype) for shape, dtype in shapes_dtypes]


def _concrete_warmup_leaf(leaf: Any) -> Any:
    """Example leaf -> concrete dummy: ShapeDtypeStructs become zeros, arrays
    are replaced by zeros of their aval (never dispatch on the user's data),
    scalars/bools pass through."""
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return jnp.zeros(leaf.shape, leaf.dtype)
    if _is_concrete_array(leaf):
        return jnp.zeros(np.shape(leaf), leaf.dtype)
    return leaf


def _normalize_warmup_specs(batch_specs: Any) -> List[Tuple[tuple, dict]]:
    """Accept one spec or a sequence of specs; each spec is an args tuple
    (optionally an ``(args_tuple, kwargs_dict)`` pair) of arrays /
    ``ShapeDtypeStruct`` leaves. Returns concrete ``(args, kwargs)`` dummies.
    """
    if isinstance(batch_specs, tuple) and batch_specs and not isinstance(batch_specs[0], (tuple, list)):
        batch_specs = [batch_specs]  # a single bare args tuple
    out: List[Tuple[tuple, dict]] = []
    for spec in batch_specs:
        if (
            isinstance(spec, (tuple, list))
            and len(spec) == 2
            and isinstance(spec[0], (tuple, list))
            and isinstance(spec[1], dict)
        ):
            args, kwargs = tuple(spec[0]), dict(spec[1])
        elif isinstance(spec, (tuple, list)):
            args, kwargs = tuple(spec), {}
        else:
            args, kwargs = (spec,), {}
        out.append(
            (
                tuple(_concrete_warmup_leaf(a) for a in args),
                {k: _concrete_warmup_leaf(v) for k, v in kwargs.items()},
            )
        )
    return out


class WarmupHandle:
    """Handle for a background :meth:`warmup` run: ``wait()`` joins the
    thread and returns the report dict; ``done`` polls."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._report: Optional[Dict[str, Any]] = None
        self._error: Optional[BaseException] = None

    def _run(self, body: Callable, jobs: Any, ladder: bool) -> None:
        try:
            self._report = body(jobs, ladder)
        except BaseException as err:  # surfaced on wait(), never lost
            self._error = err
            rank_zero_debug(f"torchmetrics_tpu warmup thread failed: {type(err).__name__}: {err}")

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                return None  # still warming; call wait() again
        if self._error is not None:
            raise self._error
        return self._report


def bucket_size(n: int) -> int:
    """Next rung of the geometric bucket ladder: powers of two, floor 8.

    >>> [bucket_size(n) for n in (1, 8, 9, 100, 1024)]
    [8, 8, 16, 128, 1024]
    """
    n = int(n)
    if n <= _BUCKET_FLOOR:
        return _BUCKET_FLOOR
    return 1 << (n - 1).bit_length()


_trace_probe_logged = False


def _trace_clean() -> bool:
    global _trace_probe_logged
    try:
        return bool(jax.core.trace_state_clean())
    except Exception as err:
        # jax moved/renamed this probe across versions; assume an untraced
        # context but say so once instead of silently guessing forever
        if not _trace_probe_logged:
            _trace_probe_logged = True
            rank_zero_debug(
                f"torchmetrics_tpu executor: jax.core.trace_state_clean unavailable"
                f" ({type(err).__name__}: {err}); assuming untraced context"
            )
        return True


def _is_concrete_array(x: Any) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) and not isinstance(x, jax.core.Tracer)


def _classify_leaves(leaves: Sequence[Any]):
    """Per-leaf signature, or None when any leaf cannot cross a jit boundary.

    Python ``bool`` leaves key on their VALUE: they stay static (closed over
    per executable) so flag arguments like FID's ``update(imgs, real=True)``
    keep driving Python control flow instead of becoming tracers.
    """
    sig: List[Any] = []
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            return None
        if type(leaf) is bool:
            sig.append(("static_bool", leaf))
        elif _is_concrete_array(leaf):
            arr = leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
            sig.append((tuple(arr.shape), str(arr.dtype)))
        elif isinstance(leaf, _PY_SCALARS):
            sig.append(("py", type(leaf).__name__))
        else:
            return None
    return tuple(sig)


def _split_static_bools(leaves: Sequence[Any]):
    """(dynamic leaves, ((index, value), ...)) — bools are closed over, not traced."""
    dyn: List[Any] = []
    spec: List[Tuple[int, bool]] = []
    for i, leaf in enumerate(leaves):
        if type(leaf) is bool:
            spec.append((i, leaf))
        else:
            dyn.append(leaf)
    return dyn, tuple(spec)


def _merge_static_bools(dyn: Sequence[Any], spec: Tuple[Tuple[int, bool], ...], total: int) -> List[Any]:
    fixed = dict(spec)
    it = iter(dyn)
    return [fixed[i] if i in fixed else next(it) for i in range(total)]


def _common_batch_dim(leaves: Sequence[Any]) -> Optional[int]:
    """The shared leading dim of every >=1-d array leaf, if one exists."""
    dims = set()
    for leaf in leaves:
        if _is_concrete_array(leaf) and getattr(leaf, "ndim", 0) >= 1:
            dims.add(int(leaf.shape[0]))
    if len(dims) != 1:
        return None
    return dims.pop()


def _pad_leaves(leaves: Sequence[Any], batched: Sequence[bool], pad_to: int) -> List[Any]:
    """Pad each batched leaf's leading dim to ``pad_to`` with copies of row 0."""
    out: List[Any] = []
    for leaf, is_batched in zip(leaves, batched):
        if not is_batched:
            out.append(leaf)
            continue
        arr = jnp.asarray(leaf)
        n = arr.shape[0]
        if n == pad_to:
            out.append(arr)
        else:
            fill = jnp.broadcast_to(arr[:1], (pad_to - n,) + arr.shape[1:])
            out.append(jnp.concatenate([arr, fill], axis=0))
    return out


def _row0_leaves(leaves: Sequence[Any], batched: Sequence[bool]) -> List[Any]:
    return [leaf[:1] if is_batched else leaf for leaf, is_batched in zip(leaves, batched)]


def _tree_copy(state: Dict[str, Any]) -> Dict[str, Any]:
    return {k: jnp.array(v, copy=True) for k, v in state.items()}


def _states_close(a: Dict[str, Any], b: Dict[str, Any], fields) -> bool:
    for k in fields:
        x, y = np.asarray(a[k]), np.asarray(b[k])
        if x.shape != y.shape:
            return False
        if np.issubdtype(x.dtype, np.floating):
            if not np.allclose(x, y, rtol=1e-4, atol=1e-6, equal_nan=True):
                return False
        elif not np.array_equal(x, y):
            return False
    return True


def _values_close(a: Any, b: Any) -> bool:
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or not np.allclose(x, y, rtol=1e-4, atol=1e-6, equal_nan=True):
            return False
    return True


def _subtract_pad_contribution(
    metric: Any,
    updated: Dict[str, Any],
    defaults: Dict[str, Any],
    init_const: Dict[str, Any],
    row0_args: tuple,
    row0_kwargs: dict,
    extra: Any,
) -> Dict[str, Any]:
    """Remove the padding rows' contribution from an updated state pytree.

    ``extra`` (traced scalar) is the number of padded rows, each a copy of the
    batch's first row. For per-sample-additive ``"sum"`` states the padding
    adds exactly ``extra * (update(init, row0) - default)``; duplicated real
    rows can never change a ``max``/``min`` state. Validity is probed
    empirically on the first padded call (see module docstring).
    """
    d1 = metric.functional_update(init_const, *row0_args, **row0_kwargs)
    out: Dict[str, Any] = {}
    for field in metric._defaults:
        if metric._reductions.get(field) == "sum":
            contrib = d1[field] - defaults[field]
            out[field] = updated[field] - contrib * extra.astype(jnp.asarray(contrib).dtype)
        else:
            out[field] = updated[field]
    return out


def _new_stats() -> Dict[str, Any]:
    return {
        "calls": 0,          # executor actually ran the computation
        "compiles": 0,       # distinct cache keys built (one XLA compile each)
        "cache_hits": 0,     # calls served by a warm executable
        "padded_calls": 0,   # calls that padded a ragged batch up the ladder
        "donated_calls": 0,  # calls that donated the live state buffers
        "copied_calls": 0,   # calls that copied first (escaped/shared/fresh key)
        "probes": 0,         # eager oracle runs validating padded execution
        "skipped_calls": 0,  # per-call ineligibility (tracers, odd inputs)
        "dispatch_failures": 0,   # warm-executable failures propagated to the caller
        "recovery_restores": 0,   # donated states reinstalled from the host snapshot
        "dispatch_retries": 0,    # warm failures re-attempted after the restore (io/retry.py)
        # compile-ahead layer (ops/compile_cache.py; docs/EXECUTOR.md)
        "disk_hits": 0,           # keys served from the persistent executable store
        "disk_stores": 0,         # fresh compiles exported + persisted to disk
        "disk_evictions": 0,      # persisted entries that failed at dispatch and were dropped
        "background_compiles": 0, # cold keys compiled on the worker and swapped in warm
        "eager_misses": 0,        # calls served eagerly while their compile ran in background
        # duration keys standardize on _us (ISSUE 6 satellite)
        "compile_us_total": 0.0,  # wall-clock spent in cold (trace+compile) dispatches
        "warmup": 0,              # executables precompiled through the warmup API
    }


class _ExecutorBase:
    """Shared cache/stats/flag plumbing for metric- and collection-executors."""

    def __init__(self) -> None:
        self._cache: Dict[Any, Callable] = {}
        self.stats = _new_stats()
        # global telemetry aggregation (obs/registry.py): weak registration,
        # zero hot-path cost — stats stay plain dict increments here and the
        # registry sums them only when telemetry_snapshot() is asked
        obs.register_executor(self)
        self.disabled_reason: Optional[str] = None
        self._static_reason_cached: Any = ()  # sentinel: not yet computed
        self._pad_validated = False
        self._bucketing_ok = True
        self._keep_recovery = recovery_enabled_default()
        # compile-ahead bookkeeping (ops/compile_cache.py): the lock guards
        # cache/pending mutations shared with the background worker thread
        self._cache_lock = threading.Lock()
        self._pending_keys: set = set()
        self._disk_checked: set = set()
        self._bg_compile: Optional[bool] = None  # None -> env default
        self._profile: Dict[str, Dict[str, Any]] = {}  # replayable shape specs
        self._profile_keys: set = set()  # cache keys already profiled (O(1) warm-path gate)
        self._state_sig_memo: Any = None  # (layout_version, sig) — see _state_sig
        # most recent committed donating call's host-side recovery snapshot,
        # kept so the Autosaver (io/checkpoint.py) can serialize it instead of
        # fetching the live state again — zero extra device sync per autosave.
        # MetricExecutor: (described_update_count, {field: np}); Collection:
        # {leader: (count, {field: np})}. None when the last call copied.
        self._last_recovery: Any = None

    def _owner_name(self) -> str:
        return type(self).__name__

    def _disable(self, reason: str) -> None:
        """Permanently fall back to the eager path, RECORDING why (ISSUE 2
        satellite: a metric silently running 20× slower must be diagnosable).
        The reason surfaces via ``Metric.executor_status`` /
        :func:`executor_stats` and is logged once at debug level."""
        if self.disabled_reason is None:
            rank_zero_debug(
                f"torchmetrics_tpu executor disabled for {self._owner_name()}: {reason}"
                " (eager fallback; see Metric.executor_status)"
            )
            obs.fault_breadcrumb(
                "executor_disabled",
                domain="dispatch",
                data={"owner": self._owner_name(), "reason": reason},
            )
        self.disabled_reason = reason

    def _snapshot(self, state: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Host-side recovery reference taken right before a donating call: if
        the dispatch dies after the runtime took the buffers, this is the only
        surviving copy of the accumulated state. ``None`` when recovery is
        disabled via the env flag.

        ``np.array`` (copying) rather than ``jax.device_get``: on CPU backends
        device_get can return a zero-copy VIEW of the device buffer, which an
        in-place donating dispatch then overwrites — silently corrupting the
        very snapshot that exists to survive it."""
        if not self._keep_recovery:
            return None
        return {k: np.array(v) for k, v in state.items()}

    def _take_recovery(self, metric: Any, state: Dict[str, Any], args: tuple) -> Any:
        """The recovery reference for a donating call: a metric-provided
        partial snapshot when the metric offers one (``_recovery_snapshot`` —
        LanedMetric's incremental lane mirror, which copies only the rows a
        round touches instead of the whole stacked state), else the classic
        full host copy."""
        if not self._keep_recovery:
            return None
        hook = getattr(metric, "_recovery_snapshot", None)
        if hook is not None:
            snap = hook(state, args)
            if snap is not None:
                return snap
        return self._snapshot(state)

    def _restore(self, metric: Any, recovery: Any) -> None:
        """Reinstall a recovery snapshot (or defaults when recovery is off)
        into ``metric`` after a donated dispatch failed."""
        if recovery is not None and hasattr(recovery, "as_state"):
            restored = recovery.as_state()
            self.stats["recovery_restores"] += 1
        elif recovery is not None:
            restored = {k: jnp.asarray(v) for k, v in recovery.items()}
            self.stats["recovery_restores"] += 1
        else:
            restored = {k: jnp.asarray(v) for k, v in metric._defaults.items()}
            rank_zero_debug(
                f"torchmetrics_tpu executor: dispatch failed after donation with"
                f" {RECOVERY_ENV_FLAG}=0 — state of {type(metric).__name__} reset to defaults"
            )
        new_state = dict(metric._state)
        new_state.update(restored)
        object.__setattr__(metric, "_state", new_state)
        metric.__dict__["_state_escaped"] = True

    def _guarded_dispatch(
        self,
        primary: Callable[[], Any],
        retry_call: Callable[[], Any],
        fresh: bool,
        restore: Callable[[], None],
    ) -> Any:
        """Run a compiled dispatch under the stall watchdog with transient-
        failure retries (io/retry.py; docs/DURABILITY.md).

        ``primary`` may donate live buffers; ``retry_call`` must build its own
        input copies (it runs only after ``restore`` reinstalled the recovery
        snapshot, so the live state is valid again and retries can never
        double-donate). A fresh key's failure propagates raw (trace/compile
        problem — the sticky eager fallback upstream is correct); a warm
        failure exhausting its retry budget raises :class:`_DispatchFailure`
        wrapping the final error. A :class:`DispatchStallError` is never
        retried: re-running a call that just hung for its whole deadline would
        park the loop for another one.
        """
        from torchmetrics_tpu.io.retry import (
            RetryPolicy,
            backoff_delays,
            default_dispatch_deadline,
            default_dispatch_retries,
            stall_watchdog,
        )

        deadline = default_dispatch_deadline()

        def once(call: Callable[[], Any]) -> Any:
            with stall_watchdog(
                deadline, what=f"donated dispatch for {self._owner_name()}", status=self.stats_dict
            ):
                return call()

        try:
            return once(primary)
        except Exception as err:
            if fresh:
                raise  # trace/compile failure: live state was never at risk
            restore()
            self.stats["dispatch_failures"] += 1
            retries = default_dispatch_retries()
            if retries and not isinstance(err, DispatchStallError):
                for delay in backoff_delays(RetryPolicy(max_retries=retries)):
                    time.sleep(delay)
                    self.stats["dispatch_retries"] += 1
                    try:
                        return once(retry_call)
                    except DispatchStallError as stalled:
                        err = stalled
                        break
                    except Exception as again:
                        rank_zero_debug(
                            f"torchmetrics_tpu executor: retry dispatch for {self._owner_name()}"
                            f" failed again ({type(again).__name__}: {again})"
                        )
                        err = again
            raise _DispatchFailure(err)

    # ----------------------------------------------------- compile-ahead layer
    def background_enabled(self) -> bool:
        """Whether cold keys compile on the background worker (per-instance
        override, else the ``TORCHMETRICS_TPU_BG_COMPILE`` env default)."""
        if self._bg_compile is not None:
            return self._bg_compile
        return compile_cache.background_compile_default()

    def set_background_compile(self, enabled: Optional[bool]) -> None:
        """Override stall-free background compilation for this executor
        (None restores the env default)."""
        self._bg_compile = enabled

    def _install_fn(self, key: Any, fn: Callable) -> None:
        with self._cache_lock:
            self._cache[key] = fn
            self._pending_keys.discard(key)

    def _load_from_disk(self, key: Any, persist: _PersistSpec) -> Optional[Callable]:
        """Deserialize a persisted executable for ``key``, or None on miss.

        The returned callable routes its first-dispatch failure to
        :class:`_DiskEntryFailure` (evict + fresh recompile, NOT the sticky
        eager fallback a trace failure earns) and unwraps itself back to the
        bare jitted call once one dispatch has succeeded."""
        with obs.span(obs.SPAN_CACHE_LOAD, owner=self._owner_name()):
            sections = compile_cache.load_executable_blob(persist.key_desc)
            if sections is None:
                return None
            loaded = None
            for fmt, blob in sections:  # best format first; fall through on failure
                try:
                    loaded = compile_cache.deserialize_executable(blob, fmt)
                    break
                except Exception as err:
                    rank_zero_debug(
                        f"torchmetrics_tpu compile cache: section {fmt!r} for {self._owner_name()}"
                        f" failed to deserialize ({type(err).__name__}: {err}); trying next section"
                    )
        if loaded is None:
            rank_zero_warn(
                f"torchmetrics_tpu compile cache: persisted executable for {self._owner_name()}"
                f" failed to deserialize (no loadable section); recompiling fresh"
            )
            self._unlink_entry(persist.key_desc)
            return None
        proven = [False]

        def dispatch(*args: Any) -> Any:
            if proven[0]:
                return loaded(*args)
            try:
                out = loaded(*args)
            except Exception as err:
                raise _DiskEntryFailure(key, persist.key_desc, err) from err
            proven[0] = True
            self._install_fn(key, loaded)  # drop this wrapper from the hot path
            return out

        return dispatch

    def _unlink_entry(self, key_desc: str) -> None:
        path = compile_cache.entry_path(compile_cache.entry_key(key_desc))
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                rank_zero_debug(f"torchmetrics_tpu compile cache: could not delete {path}")

    def _evict_disk_entry(self, failure: _DiskEntryFailure) -> None:
        """A persisted executable died at dispatch: drop it from memory and
        disk so the retry compiles fresh (docs/EXECUTOR.md "Compile-ahead")."""
        with self._cache_lock:
            self._cache.pop(failure.key, None)
        self._unlink_entry(failure.key_desc)
        self.stats["disk_evictions"] += 1
        obs.fault_breadcrumb(
            "disk_entry_evicted",
            domain="compile",
            data={"owner": self._owner_name(), "error": f"{type(failure.original).__name__}: {failure.original}"},
        )
        rank_zero_warn(
            f"torchmetrics_tpu compile cache: persisted executable for {self._owner_name()}"
            f" failed at dispatch ({type(failure.original).__name__}: {failure.original});"
            " entry evicted, recompiling fresh"
        )

    def _schedule_background_compile(self, key: Any, persist: _PersistSpec) -> bool:
        """Compile ``key`` on the shared worker (tracing a detached clone),
        warm it on zero dummies, and swap it into the cache; the current step
        proceeds eagerly. A full queue or un-clonable owner skips (False:
        the caller compiles inline); a failing trace sticky-disables exactly
        like an inline trace failure would."""
        with self._cache_lock:
            if key in self._pending_keys:
                return True  # already compiling: keep serving eagerly
            self._pending_keys.add(key)
        try:
            clone_builder = persist.make_clone_builder()
        except Exception as err:
            rank_zero_debug(
                f"torchmetrics_tpu executor: {self._owner_name()} is not clonable for background"
                f" compilation ({type(err).__name__}: {err}); compiling inline"
            )
            with self._cache_lock:
                self._pending_keys.discard(key)
            return False

        def job() -> None:
            t0 = time.perf_counter()
            try:
                with obs.span(obs.SPAN_COMPILE, owner=self._owner_name(), background=True):
                    fn = jax.jit(clone_builder(), donate_argnums=0)
                    jax.block_until_ready(fn(*persist.dummy_args()))
            except Exception as err:
                with self._cache_lock:
                    self._pending_keys.discard(key)
                self._disable(f"background compile failed: {type(err).__name__}: {err}")
                return
            self._install_fn(key, fn)
            self.stats["compiles"] += 1
            self.stats["background_compiles"] += 1
            self.stats["compile_us_total"] += (time.perf_counter() - t0) * 1e6
            self._persist_body(fn, persist)

        # the enqueue span is the flow source the worker-side compile span
        # links back to (Perfetto flow arrow: miss site -> worker replay)
        with obs.span(obs.SPAN_COMPILE, owner=self._owner_name(), phase="enqueue"):
            submitted = compile_cache.get_worker().submit(job)
        if not submitted:
            with self._cache_lock:
                self._pending_keys.discard(key)
            return False
        return True

    def _schedule_persist(self, persist: _PersistSpec) -> None:
        """Persist a freshly inline-compiled key in the background (skipped
        when an identical entry already exists — e.g. a sibling instance of
        the same metric config got there first). The worker re-traces a
        DETACHED clone for export: the live jitted callable's trace would
        swap live state mid-step (see :class:`_PersistSpec`)."""
        path = compile_cache.entry_path(compile_cache.entry_key(persist.key_desc))
        if path is None or os.path.exists(path):
            return
        try:
            clone_builder = persist.make_clone_builder()
        except Exception as err:
            rank_zero_debug(
                f"torchmetrics_tpu executor: {self._owner_name()} is not clonable for background"
                f" persist ({type(err).__name__}: {err}); key stays memory-only"
            )
            return
        with obs.span(obs.SPAN_CACHE_STORE, owner=self._owner_name(), phase="enqueue"):
            compile_cache.get_worker().submit(
                lambda: self._persist_body(jax.jit(clone_builder(), donate_argnums=0), persist)
            )

    def _persist_body(self, fn: Callable, persist: _PersistSpec) -> None:
        """Worker-side: export the computation at its avals, atomically store
        it, and pre-warm the persisted form into the XLA persistent cache so
        the NEXT process's first dispatch is a cache hit, not a compile."""
        try:
            with obs.span(obs.SPAN_CACHE_STORE, owner=self._owner_name()):
                sections = compile_cache.export_executable(fn, persist.avals)
        except Exception as err:
            # unserializable computation: this key stays memory-only (the XLA
            # persistent cache still covers its compile); record why once
            rank_zero_debug(
                f"torchmetrics_tpu compile cache: export failed for {self._owner_name()}"
                f" ({type(err).__name__}: {err}); key stays memory-only"
            )
            return
        if compile_cache.store_executable(persist.key_desc, sections) is None:
            return
        self.stats["disk_stores"] += 1
        if sections[0][0] != compile_cache.FORMAT_STABLEHLO:
            return  # native-executable entries reload without compiling
        try:
            # StableHLO-first entries still compile at reload: pre-populate the
            # XLA persistent cache so the NEXT process's dispatch is a cache hit
            warm = compile_cache.deserialize_executable(sections[0][1], sections[0][0])
            jax.block_until_ready(warm(*persist.dummy_args()))
        except Exception as err:
            rank_zero_debug(
                f"torchmetrics_tpu compile cache: could not pre-warm persisted entry"
                f" ({type(err).__name__}: {err})"
            )

    def _get_fn(
        self,
        key: Any,
        builder: Callable[[], Callable],
        persist_factory: Optional[Callable[[], Optional[_PersistSpec]]] = None,
        allow_background: bool = True,
    ) -> Tuple[Optional[Callable], bool]:
        """Resolve ``key`` to a dispatchable callable.

        Resolution order: warm in-memory executable -> persistent disk store
        (``disk_hits``; first dispatch keeps fresh-key copy semantics) ->
        background compile (returns ``(None, False)``: the caller serves this
        step through the eager body while the worker compiles) -> inline
        ``jax.jit`` build (the pre-compile-ahead behavior), persisted to disk
        in the background. ``persist_factory`` is only invoked on a miss —
        warm calls pay zero compile-ahead overhead."""
        fn = self._cache.get(key)
        if fn is not None:
            self.stats["cache_hits"] += 1
            return fn, False
        persist = None
        if persist_factory is not None and compile_cache.compile_ahead_enabled():
            persist = persist_factory()
        if persist is not None:
            compile_cache.ensure_xla_cache_configured()
            if key not in self._disk_checked:
                self._disk_checked.add(key)
                loaded = self._load_from_disk(key, persist)
                if loaded is not None:
                    self._install_fn(key, loaded)
                    self.stats["disk_hits"] += 1
                    return loaded, True  # fresh semantics: first dispatch copies
            if allow_background and self.background_enabled() and self._schedule_background_compile(key, persist):
                return None, False
        fn = jax.jit(builder(), donate_argnums=0)
        self._install_fn(key, fn)
        self.stats["compiles"] += 1
        if persist is not None:
            self._schedule_persist(persist)
        return fn, True

    # -------------------------------------------------- shape-profile manifest
    def _record_profile(self, key: Any, kind: str, args: tuple, kwargs: dict) -> None:
        """Remember a replayable description of this call's shapes (bounded;
        the manifest ``warmup_from_manifest`` replays in a later process).
        Gated on the cache key so warm calls pay one set lookup, not a
        spec serialization."""
        if key in self._profile_keys:
            return
        self._profile_keys.add(key)
        if len(self._profile) >= 64:
            return
        spec = compile_cache.spec_of_call(kind, args, kwargs)
        if spec is None:
            return
        self._profile.setdefault(json.dumps(spec, sort_keys=True), spec)

    def shape_profile(self) -> Dict[str, Any]:
        """Replayable manifest of every (bounded) distinct call shape this
        executor has seen — feed to ``warmup_from_manifest`` after a restart
        to precompile exactly the buckets the previous run used."""
        return {
            "profile_version": compile_cache.PROFILE_VERSION,
            "owner": self._owner_name(),
            "specs": list(self._profile.values()),
        }

    # ------------------------------------------------------------------ warmup
    def _warmup_one(self, kind: str, args: tuple, kwargs: dict) -> str:
        raise NotImplementedError

    def _warmup_bucketable(self) -> bool:
        raise NotImplementedError

    def _ladder_variants(self, args: tuple, kwargs: dict) -> List[Tuple[tuple, dict]]:
        """The spec itself plus one padded representative per bucket rung at
        or below its bucket — precompiling the ladder means the ragged final
        batches of an epoch land on warm executables too."""
        out = [(args, kwargs)]
        spec = compile_cache.spec_of_call("x", args, kwargs)
        if spec is None or not self._warmup_bucketable():
            return out
        dims = {s["shape"][0] for s in list(spec["args"]) + list(spec["kwargs"].values()) if s.get("shape")}
        if len(dims) != 1:
            return out
        n = dims.pop()
        if n <= 0:
            return out
        rung = _BUCKET_FLOOR
        top = bucket_size(n)
        while rung <= top:
            size = max(1, rung - 1)  # pads up to exactly this rung
            if size != n:
                resized = json.loads(json.dumps(spec))
                for leaf in list(resized["args"]) + list(resized["kwargs"].values()):
                    if leaf.get("shape") and leaf["shape"][0] == n:
                        leaf["shape"][0] = size
                out.append(compile_cache.dummy_from_spec(resized))
            rung <<= 1
        return out

    def warmup(
        self,
        batch_specs: Any,
        forward: bool = False,
        ladder: bool = True,
        background: bool = False,
    ) -> Any:
        """Precompile the executables ``batch_specs``-shaped traffic will hit.

        ``batch_specs``: one spec or a sequence of specs, each a tuple of
        example arrays / ``jax.ShapeDtypeStruct`` leaves (optionally
        ``(args_tuple, kwargs_dict)``). Values are irrelevant — zero-filled
        dummies are compiled and discarded; live state is never touched.
        ``ladder=True`` additionally warms one padded representative per
        bucket rung. ``background=True`` runs on a daemon thread and returns
        a :class:`WarmupHandle`; otherwise the report dict is returned.
        """
        jobs = [("update", a, k) for a, k in _normalize_warmup_specs(batch_specs)]
        if forward:
            jobs += [("forward", a, k) for _, a, k in jobs[: len(jobs)]]
        return self._launch_warmup(jobs, ladder, background)

    def warmup_from_manifest(self, manifest: Any, background: bool = False) -> Any:
        """Replay a shape-profile manifest (a dict from :meth:`shape_profile`
        or a path saved by ``save_shape_profile``): precompiles exactly the
        call shapes a previous run recorded, no ladder expansion."""
        if isinstance(manifest, (str, os.PathLike)):
            manifest = compile_cache.load_shape_manifest(os.fspath(manifest))
        if not isinstance(manifest.get("specs"), list):
            raise ValueError("manifest has no 'specs' list")
        jobs = []
        for spec in manifest["specs"]:
            args, kwargs = compile_cache.dummy_from_spec(spec)
            jobs.append((spec.get("kind", "update"), args, kwargs))
        return self._launch_warmup(jobs, ladder=False, background=background)

    def _launch_warmup(self, jobs: List[Tuple[str, tuple, dict]], ladder: bool, background: bool) -> Any:
        if not background:
            return self._run_warmup(jobs, ladder)
        handle = WarmupHandle()
        thread = threading.Thread(
            target=handle._run, args=(self._run_warmup, jobs, ladder), name="tm_tpu_warmup", daemon=True
        )
        handle._thread = thread
        thread.start()
        return handle

    def _run_warmup(self, jobs: List[Tuple[str, tuple, dict]], ladder: bool) -> Dict[str, Any]:
        t0 = time.perf_counter()
        report: Dict[str, Any] = {"warmed": 0, "already_warm": 0, "skipped": []}
        for kind, args, kwargs in jobs:
            variants = self._ladder_variants(args, kwargs) if ladder else [(args, kwargs)]
            for v_args, v_kwargs in variants:
                try:
                    outcome = self._warmup_one(kind, v_args, v_kwargs)
                except Exception as err:  # warmup must never take the loop down
                    outcome = f"{kind}: {type(err).__name__}: {err}"
                    rank_zero_debug(f"torchmetrics_tpu warmup: {self._owner_name()}: {outcome}")
                if outcome == "warmed":
                    report["warmed"] += 1
                elif outcome == "already_warm":
                    report["already_warm"] += 1
                else:
                    report["skipped"].append(outcome)
        report["seconds"] = round(time.perf_counter() - t0, 3)
        return report

    def _dispatch_warmup(self, key: Any, builder: Callable[[], Callable], persist: _PersistSpec) -> str:
        """Shared tail of every warmup path: resolve the key inline (disk
        store consulted, background-miss mode bypassed — warmup IS the
        background) and prove the executable with one dummy dispatch.

        Tracing goes through a detached clone, not ``builder`` bound to the
        live object: warmup may run on its own thread while traffic flows,
        and tracing the live metric would swap its state mid-step."""
        del builder  # the live-bound builder must not trace off-thread
        if key in self._cache:
            return "already_warm"
        t0 = time.perf_counter()
        with obs.span(obs.SPAN_WARMUP, owner=self._owner_name()):
            clone_builder = persist.make_clone_builder()
            fn, _ = self._get_fn(key, clone_builder, lambda: persist, allow_background=False)
            try:
                jax.block_until_ready(fn(*persist.dummy_args()))
            except _DiskEntryFailure as df:
                self._evict_disk_entry(df)
                fn, _ = self._get_fn(key, clone_builder, None, allow_background=False)
                jax.block_until_ready(fn(*persist.dummy_args()))
        self.stats["warmup"] += 1
        self.stats["compile_us_total"] += (time.perf_counter() - t0) * 1e6
        return "warmed"

    def stats_dict(self) -> Dict[str, Any]:
        out = dict(self.stats)
        out["disabled_reason"] = self.disabled_reason
        out["fallback_reason"] = self.disabled_reason
        out["bucketing_enabled"] = self._bucketing_ok
        out["cached_executables"] = len(self._cache)
        out["background_enabled"] = self.background_enabled()
        out["pending_background"] = len(self._pending_keys)
        out["profile_entries"] = len(self._profile)
        return out


class MetricExecutor(_ExecutorBase):
    """Per-``Metric`` executor: compiled update/forward with donated state."""

    def __init__(self, metric: Any, plain_functional: bool, plain_forward: bool) -> None:
        super().__init__()
        self._metric = metric
        self._plain_functional = plain_functional
        self._plain_forward = plain_forward

    def _owner_name(self) -> str:
        return type(self._metric).__name__

    # ------------------------------------------------------------ eligibility
    def _static_reason(self) -> Optional[str]:
        if self._static_reason_cached != ():
            return self._static_reason_cached
        m = self._metric
        reason = None
        if not self._plain_functional:
            reason = "functional_update/functional_compute overridden"
        elif getattr(m, "executor_compatible", True) is False:
            reason = "metric declares executor_compatible=False"
        elif not m._defaults:
            reason = "no registered states"
        elif any(isinstance(v, list) for v in m._defaults.values()):
            reason = "list states change pytree structure every update"
        elif m.compute_on_cpu:
            reason = "compute_on_cpu moves states host-side after update"
        elif getattr(m, "validate_args", None) is True:
            reason = "validate_args=True needs concrete input checks"
        else:
            hook = getattr(m, "_executor_traceable", None)
            if callable(hook) and not hook():
                reason = "metric declares itself untraceable"
        self._static_reason_cached = reason
        return reason

    def usable(self) -> bool:
        return self.disabled_reason is None and self._static_reason() is None

    def stats_dict(self) -> Dict[str, Any]:
        out = super().stats_dict()
        if out["disabled_reason"] is None:
            out["disabled_reason"] = self._static_reason()
        out["fallback_reason"] = out["disabled_reason"]
        return out

    def bucketable(self) -> bool:
        if not self._bucketing_ok:
            return False
        m = self._metric
        # a metric can declare its update non-row-additive (laned scatter
        # updates route rows to lanes — duplicating row 0 would double-scatter)
        if getattr(m, "_executor_bucketable", True) is False:
            return False
        for field, fx in m._reductions.items():
            if fx not in _FUSABLE_REDUCTIONS:
                return False
            if fx == "sum" and jnp.asarray(m._defaults[field]).dtype == jnp.bool_:
                return False
        return True

    # ----------------------------------------------------- compile-ahead keys
    def _owner_desc(self) -> str:
        """Cross-process identity of this metric's computation: class +
        defining-module source hash + the registered state spec (shapes carry
        configuration like ``num_classes``; reductions carry merge semantics)."""
        import sys

        m = self._metric
        cls = type(m)
        mod = sys.modules.get(cls.__module__)
        fields = ",".join(
            f"{k}:{jnp.asarray(v).dtype}:{tuple(np.shape(v))}:{m._reductions.get(k)}"
            for k, v in m._defaults.items()
        )
        # wrappers whose computation depends on an INNER metric (LanedMetric
        # vmaps inner.functional_update) contribute that identity too — two
        # wrappers with identical state specs but different inner updates must
        # never share a persisted executable
        extra = getattr(m, "_executor_identity", None)
        ident = f"|inner={extra()}" if callable(extra) else ""
        # trace-affecting config invisible to the state spec (an aggregator's
        # nan_strategy, a laned wrapper's device-side row screen, a
        # class-axis state_sharding layout whose stacked shape aliases some
        # dense state's): two instances whose compiled computation differs
        # must never share a persisted executable
        cfg = ",".join(map(str, m._trace_config()))
        cfg = f"|cfg={cfg}" if cfg else ""
        return f"{cls.__module__}.{cls.__qualname__}@{compile_cache.source_hash(mod or cls)}|{fields}{ident}{cfg}"

    def _key_desc(self, key: Any) -> str:
        return "|".join(
            (
                compile_cache.toolchain_fingerprint(),
                compile_cache.backend_fingerprint(),
                self._owner_desc(),
                _stable_key_repr(key),
                "donate=0",
            )
        )

    def _state_sig(self) -> Tuple[Any, ...]:
        """Shape/dtype signature of the registered state — part of every cache
        key so a metric whose state layout can change at runtime (a LanedMetric
        growing its lane capacity) resolves to a NEW executable through
        ``_get_fn`` (and so the persistent disk store / warmed entries) instead
        of silently retracing inside a stale cached ``jax.jit`` callable.

        Memoized per ``_state_layout_version`` — ``_defaults`` is immutable
        after ``add_state`` for every metric except the laned wrappers, which
        bump the version on every growth/respec — so the steady dispatch path
        pays one integer getattr, not a rebuilt shape/dtype tuple per call."""
        ver = getattr(self._metric, "_state_layout_version", 0)
        cached = self._state_sig_memo
        if cached is not None and cached[0] == ver:
            return cached[1]
        sig = (
            ver,
            tuple(
                (k, tuple(np.shape(v)), str(getattr(v, "dtype", type(v).__name__)))
                for k, v in self._metric._defaults.items()
            ),
        )
        self._state_sig_memo = (ver, sig)
        return sig

    def _clone_owner(self):
        """A fully-detached deep copy of the metric for off-main-thread
        tracing (``__getstate__`` rebuilds the wrapped methods around the
        copy, so no closure reaches back to the live instance); its own
        executor is disabled so a clone can never recurse into this machinery."""
        import copy

        clone = copy.deepcopy(self._metric)
        clone.__dict__["_executor_enabled"] = False
        return clone

    def _persist_spec(
        self,
        key: Any,
        state: Dict[str, Any],
        call_leaves: Sequence[Any],
        padded: bool,
        n: Optional[int],
        count: bool,
        clone_factory: Callable[[Any], Callable],
    ) -> Optional[_PersistSpec]:
        """Export/warm description of one executable, or None when a leaf
        cannot be described as a strong-typed aval (python-scalar leaves trace
        weakly typed; a persisted strong-typed signature would not match).
        ``clone_factory(clone_metric) -> raw body`` rebuilds the builder over
        a detached clone for background tracing."""
        if not all(_is_concrete_array(l) for l in call_leaves):
            return None
        state_sd = {k: (tuple(np.shape(v)), jnp.asarray(v).dtype) for k, v in state.items()}
        leaf_sd = [(tuple(np.shape(l)), l.dtype) for l in call_leaves]
        n_val = int(n) if padded else None
        i32 = jnp.int32

        state_avals = {k: jax.ShapeDtypeStruct(s, d) for k, (s, d) in state_sd.items()}
        scalar_avals = (jax.ShapeDtypeStruct((), i32),) * (int(count) + int(padded))
        avals = (state_avals,) + scalar_avals + tuple(jax.ShapeDtypeStruct(s, d) for s, d in leaf_sd)

        def dummies() -> Tuple[Any, ...]:
            st = {k: jnp.zeros(s, d) for k, (s, d) in state_sd.items()}
            scalars = ()
            if count:
                scalars += (jnp.asarray(0, i32),)
            if n_val is not None:
                scalars += (jnp.asarray(n_val, i32),)
            return (st,) + scalars + tuple(_zeros_like_spec(leaf_sd))

        def make_clone_builder() -> Callable[[], Callable]:
            clone = self._clone_owner()
            return lambda: clone_factory(clone)

        return _PersistSpec(self._key_desc(key), avals, dummies, make_clone_builder)

    # ------------------------------------------------------------------ warmup
    def _warmup_bucketable(self) -> bool:
        return self.bucketable()

    def _warmup_one(self, kind: str, args: tuple, kwargs: dict) -> str:
        m = self._metric
        if not self.usable():
            return f"{kind}: executor unusable ({self.disabled_reason or self._static_reason()})"
        prep = self._prepare(args, kwargs)
        if prep is None:
            return f"{kind}: inputs not executor-eligible"
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        zero_state = {k: jnp.zeros(np.shape(v), jnp.asarray(v).dtype) for k, v in m._defaults.items()}
        if kind == "update":
            key = ("u", treedef, sig, batched, bucket if padded else None, self._state_sig())

            def build(metric=None):
                return self._build_update(treedef, batched, bucket, padded, bool_spec, n_leaves, metric=metric)

            persist = self._persist_spec(key, zero_state, call_leaves, padded, n, count=False, clone_factory=build)
        elif kind == "forward":
            if not self._plain_forward or m.dist_sync_on_step:
                return "forward: not fusable (custom forward or dist_sync_on_step)"
            variant = "reduce" if m.full_state_update is False else "full"
            key = ("f", variant, treedef, sig, batched, bucket if padded else None, self._state_sig())

            def build(metric=None):
                return self._build_forward(treedef, batched, bucket, padded, variant, bool_spec, n_leaves, metric=metric)

            persist = self._persist_spec(key, zero_state, call_leaves, padded, n, count=True, clone_factory=build)
        else:
            return f"{kind}: unknown warmup kind"
        if persist is None:
            return f"{kind}: inputs not persistable (python-scalar leaves)"
        return self._dispatch_warmup(key, build, persist)

    # --------------------------------------------------------------- builders
    def _build_update(self, treedef, batched, bucket, padded, bool_spec, n_leaves, metric=None):
        # ``metric`` overrides the traced instance: background jobs pass a
        # detached clone so tracing never swaps the live metric's state
        m = metric if metric is not None else self._metric
        defaults = {k: jnp.asarray(v) for k, v in m._defaults.items()}

        if not padded:
            def raw(state, *dyn):
                leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
                args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
                return m.functional_update(state, *args, **kwargs)
            return raw

        def raw(state, n_valid, *dyn):
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            g = m.functional_update(state, *args, **kwargs)
            r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            extra = jnp.asarray(bucket, jnp.int32) - n_valid
            return _subtract_pad_contribution(m, g, defaults, defaults, r_args, r_kwargs, extra)

        return raw

    def _build_forward(self, treedef, batched, bucket, padded, variant, bool_spec, n_leaves, metric=None):
        m = metric if metric is not None else self._metric
        defaults = {k: jnp.asarray(v) for k, v in m._defaults.items()}
        one = jnp.asarray(1, jnp.int32)

        def batch_state(leaves):
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            return m.functional_update(defaults, *args, **kwargs), (args, kwargs)

        def raw(state, count, *rest):
            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn = rest
                extra = None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            bs, (args, kwargs) = batch_state(leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
                bs = _subtract_pad_contribution(m, bs, defaults, defaults, r_args, r_kwargs, extra)
            value = m.functional_compute(bs)
            if variant == "reduce":
                new_state = m.merge_states(state, bs, counts=(count, one))
            else:
                new_state = m.functional_update(state, *args, **kwargs)
                if extra is not None:
                    r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
                    new_state = _subtract_pad_contribution(
                        m, new_state, defaults, defaults, r_args, r_kwargs, extra
                    )
            return new_state, value

        return raw

    # ----------------------------------------------------------------- shared
    def _prepare(self, args, kwargs):
        """Classify inputs; returns (treedef, leaves, sig, batched, bucket, n) or None.

        ``(args, kwargs)`` flatten as one pytree: dict keys live in the treedef
        (jax sorts them), so keyword order never splits the executable cache.
        """
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = _classify_leaves(leaves)
        if sig is None:
            return None
        n = _common_batch_dim(leaves)
        bucket = None
        padded = False
        if n is not None and n > 0 and self.bucketable():
            bucket = bucket_size(n)
            padded = bucket != n
        if padded:
            with obs.span(obs.SPAN_PAD, n=int(n), bucket=int(bucket)):
                batched = tuple(
                    _is_concrete_array(l) and getattr(l, "ndim", 0) >= 1 and int(l.shape[0]) == n
                    for l in leaves
                )
                call_leaves = _pad_leaves(leaves, batched, bucket)
                sig = _classify_leaves(call_leaves)
        else:
            batched = None
            call_leaves = list(leaves)
        dyn_leaves, bool_spec = _split_static_bools(call_leaves)
        return treedef, dyn_leaves, sig, batched, bucket, n, padded, bool_spec, len(call_leaves)

    # ------------------------------------------------------------------ entry
    def run_update(self, args: tuple, kwargs: dict) -> bool:
        """Execute ``update`` through the compiled path; False -> caller falls
        back to the eager body (never partially applied).

        Failure containment (docs/EXECUTOR.md "Failure semantics"): a FRESH
        key's failure is a trace/compile problem — inputs were copies, so the
        sticky eager fallback is safe. A WARM executable's failure is a
        runtime/dispatch problem after the inputs may have been donated: the
        live state has been restored from the recovery snapshot and the
        original error propagates (no silent eager re-run of the batch)."""
        if not self.usable():
            return False
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False
        try:
            return self._run_update(args, kwargs)
        except _DispatchFailure as df:
            raise df.original
        except _DiskEntryFailure as df:
            # a persisted executable died at dispatch (inputs were copies):
            # evict it and retry through a fresh inline compile
            self._evict_disk_entry(df)
            return self.run_update(args, kwargs)
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:  # sticky: a metric that cannot trace stays eager
            self._disable(f"{type(err).__name__}: {err}")
            return False

    def _run_update(self, args, kwargs) -> bool:
        prep = self._prepare(args, kwargs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        m = self._metric

        key = ("u", treedef, sig, batched, bucket if padded else None, self._state_sig())
        self._record_profile(key, "update", args, kwargs)
        state = {k: m._state[k] for k in m._defaults}

        def build(metric=None):
            return self._build_update(treedef, batched, bucket, padded, bool_spec, n_leaves, metric=metric)

        fn, fresh = self._get_fn(
            key,
            build,
            lambda: self._persist_spec(key, state, call_leaves, padded, n, count=False, clone_factory=build),
        )
        if fn is None:  # compile in flight on the worker: serve this step eagerly
            self.stats["eager_misses"] += 1
            return False

        need_copy = fresh or m._state_escaped or m._state_shared
        state_in = _tree_copy(state) if need_copy else state
        # donation in play -> keep a host-side recovery reference (ISSUE 2)
        recovery = None if need_copy else self._take_recovery(m, state, args)

        do_probe = padded and not self._pad_validated
        oracle = m.functional_update(state, *args, **kwargs) if do_probe else None

        def call_fn(state_arg):
            if padded:
                return fn(state_arg, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(state_arg, *call_leaves)

        # profiler span naming the metric so wall time attributes to it
        # (ISSUE 3 observability; the traced body carries matching
        # jax.named_scope annotations via functional_update)
        t_cold_ns = time.perf_counter_ns() if fresh else None
        with obs.span(obs.SPAN_DISPATCH, suffix=self._owner_name(), histogram="executor.dispatch_us", cold=fresh):
            new_state = self._guarded_dispatch(
                lambda: call_fn(state_in),
                lambda: call_fn(_tree_copy({k: m._state[k] for k in m._defaults})),
                fresh,
                lambda: self._restore(m, recovery) if not need_copy else None,
            )
        if t_cold_ns is not None:
            t_now_ns = time.perf_counter_ns()
            self.stats["compile_us_total"] += (t_now_ns - t_cold_ns) / 1e3
            # the cold dispatch IS the foreground compile: give it its own
            # span so a Perfetto trace separates compile stalls from warm steps
            obs.record_span(obs.SPAN_COMPILE, t_cold_ns, t_now_ns, {"owner": self._owner_name()})
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            if _states_close(new_state, oracle, m._defaults):
                self._pad_validated = True
            else:
                # bucketing is numerically unsafe for this metric: discard the
                # padded result (the live state was untouched — probe calls
                # always run on a copy) and re-dispatch through the
                # exact-shape compiled path, so every call stays consistently
                # compiled rather than one call carrying eager-flavoured
                # rounding
                self._bucketing_ok = False
                return self._run_update(args, kwargs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if need_copy else "donated_calls"] += 1
        object.__setattr__(m, "_state", dict(new_state))
        m.__dict__["_state_escaped"] = False
        # slab-aware dispatch seam (ops/ingest.py): when the lane router armed
        # a staging slab for this dispatch, a committed-state leaf becomes its
        # strong retire token — the slab is only reused once the computation
        # that consumed it finished, which keeps slab reuse safe even on
        # backends where device_put zero-copy aliases host memory. One
        # thread-local read when no slab is armed.
        _ingest_notify(new_state)
        # the wrapper bumped _update_count before this call, so the pre-call
        # recovery snapshot describes exactly count-1 committed updates — the
        # Autosaver reuses it as a free (already host-side) checkpoint source.
        # Partial (mirror) snapshots materialize a detached copy at reuse time
        # (latest_recovery_snapshot) — the mirror itself keeps folding.
        self._last_recovery = None if recovery is None else (int(m._update_count) - 1, recovery)
        return True

    def run_forward(self, args: tuple, kwargs: dict) -> Tuple[bool, Any]:
        """Execute ``forward`` as one fused ``(state, batch) -> (state', value)``
        computation. Returns ``(handled, batch_value)``."""
        m = self._metric
        if not self.usable() or not self._plain_forward or m.dist_sync_on_step:
            return False, None
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False, None
        try:
            return self._run_forward(args, kwargs)
        except _DispatchFailure as df:
            raise df.original
        except _DiskEntryFailure as df:
            self._evict_disk_entry(df)
            return self.run_forward(args, kwargs)
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return False, None

    def _forward_oracle(self, variant, state, args, kwargs, count):
        m = self._metric
        bs = m.functional_update(m.functional_init(), *args, **kwargs)
        value = m.functional_compute(bs)
        if variant == "reduce":
            new_state = m.merge_states(state, bs, counts=(count, 1))
        else:
            new_state = m.functional_update(state, *args, **kwargs)
        return new_state, value

    def _run_forward(self, args, kwargs):
        prep = self._prepare(args, kwargs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False, None
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        m = self._metric
        variant = "reduce" if m.full_state_update is False else "full"

        key = ("f", variant, treedef, sig, batched, bucket if padded else None, self._state_sig())
        self._record_profile(key, "forward", args, kwargs)
        state = {k: m._state[k] for k in m._defaults}

        def build(metric=None):
            return self._build_forward(treedef, batched, bucket, padded, variant, bool_spec, n_leaves, metric=metric)

        fn, fresh = self._get_fn(
            key,
            build,
            lambda: self._persist_spec(key, state, call_leaves, padded, n, count=True, clone_factory=build),
        )
        if fn is None:  # compile in flight on the worker: serve this step eagerly
            self.stats["eager_misses"] += 1
            return False, None

        count = int(m._update_count)
        need_copy = fresh or m._state_escaped or m._state_shared
        state_in = _tree_copy(state) if need_copy else state
        recovery = None if need_copy else self._snapshot(state)

        do_probe = padded and not self._pad_validated
        oracle = self._forward_oracle(variant, state, args, kwargs, count) if do_probe else None

        count_arr = jnp.asarray(count, jnp.int32)

        def call_fn(state_arg):
            if padded:
                return fn(state_arg, count_arr, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(state_arg, count_arr, *call_leaves)

        t_cold_ns = time.perf_counter_ns() if fresh else None
        with obs.span(obs.SPAN_DISPATCH, suffix=self._owner_name(), histogram="executor.dispatch_us", cold=fresh):
            new_state, value = self._guarded_dispatch(
                lambda: call_fn(state_in),
                lambda: call_fn(_tree_copy({k: m._state[k] for k in m._defaults})),
                fresh,
                lambda: self._restore(m, recovery) if not need_copy else None,
            )
        if t_cold_ns is not None:
            t_now_ns = time.perf_counter_ns()
            self.stats["compile_us_total"] += (t_now_ns - t_cold_ns) / 1e3
            # the cold dispatch IS the foreground compile: give it its own
            # span so a Perfetto trace separates compile stalls from warm steps
            obs.record_span(obs.SPAN_COMPILE, t_cold_ns, t_now_ns, {"owner": self._owner_name()})
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            if _states_close(new_state, oracle[0], m._defaults) and _values_close(value, oracle[1]):
                self._pad_validated = True
            else:
                # see _run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_forward(args, kwargs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if need_copy else "donated_calls"] += 1
        object.__setattr__(m, "_state", dict(new_state))
        m.__dict__["_state_escaped"] = False
        m._update_count += 1
        m._computed = None
        m._to_sync = m.sync_on_compute
        m._should_unsync = True
        # snapshot taken pre-bump: it describes count-1 committed updates
        self._last_recovery = None if recovery is None else (int(m._update_count) - 1, recovery)
        return True, value


class CollectionExecutor(_ExecutorBase):
    """Fused executor for a ``MetricCollection``: one compiled call updates (or
    forwards) EVERY compute group, with the combined leader-state pytree
    donated. Engages only when every group leader is executor-eligible;
    otherwise the collection falls back to the per-metric loop (where each
    leader still uses its own :class:`MetricExecutor`)."""

    def __init__(self, collection: Any) -> None:
        super().__init__()
        self._coll = collection

    def _owner_name(self) -> str:
        return f"MetricCollection[{', '.join(self._coll._modules)}]"

    def _cache_collection_recovery(self, donated, leader_execs) -> None:
        """Keep the step's per-group recovery snapshots for Autosaver reuse —
        only when EVERY group donated (and so has one); a partial set cannot
        describe a consistent collection-wide checkpoint."""
        if len(donated) == len(leader_execs) and all(snap is not None for *_, snap in donated):
            # _install already bumped each leader: snapshots describe count-1
            self._last_recovery = {
                name: (int(self._coll._modules[name]._update_count) - 1, snap)
                for name, _, _, snap in donated
            }
        else:
            self._last_recovery = None

    def _restore_groups(self, donated) -> None:
        """Reinstall recovery snapshots for every donated group after a failed
        fused dispatch, re-pointing followers at the leader's restored arrays."""
        mods = self._coll._modules
        for name, m, cg, recovery in donated:
            self._restore(m, recovery)
            for member in cg[1:]:
                follower = mods[member]
                for field in m._defaults:
                    follower._state[field] = m._state[field]
                follower.__dict__["_state_escaped"] = True

    # ------------------------------------------------------------ eligibility
    def _leaders(self):
        coll = self._coll
        return [(cg[0], coll._modules[cg[0]], cg) for cg in coll._groups.values()]

    def _leader_executors(self):
        out = []
        for name, m, cg in self._leaders():
            ex = m._get_executor()
            if ex is None or not ex.usable():
                return None
            if any(getattr(mm, "_executor_enabled", None) is False for mm in (self._coll._modules[x] for x in cg)):
                return None
            out.append((name, m, cg, ex))
        return out

    def bucketable(self, leader_execs) -> bool:
        return self._bucketing_ok and all(ex.bucketable() for _, _, _, ex in leader_execs)

    def _kwarg_names(self, m, kwargs) -> Tuple[str, ...]:
        return tuple(sorted(m._filter_kwargs(**kwargs)))

    def _forward_unfusable_reason(self, leader_execs) -> Optional[str]:
        """Why the fused collection forward cannot engage, or None when every
        group qualifies (reduce-merge forward: all members
        ``full_state_update=False``, no per-step sync, traceable computes)."""
        from torchmetrics_tpu.metric import Metric  # deferred: avoids import cycle

        coll = self._coll
        for _name, _m0, cg, ex in leader_execs:
            if not ex._plain_forward:
                return "a group leader overrides functional_forward/merge_states"
            for member in cg:
                mm = coll._modules[member]
                if mm.full_state_update is not False or mm.dist_sync_on_step:
                    return f"member {member!r} needs full_state_update or per-step sync"
                # every member's compute traces inside the fused call
                if type(mm).functional_compute is not Metric.functional_compute:
                    return f"member {member!r} overrides functional_compute"
        return None

    # ----------------------------------------------------- compile-ahead keys
    def _owner_desc(self) -> str:
        """Cross-process identity of the fused computation: every member's
        class + module source hash, grouped per leader, plus each leader's
        registered state spec."""
        import sys

        coll = self._coll
        parts = []
        for name, m, cg in self._leaders():
            members = ",".join(
                f"{mn}={type(coll._modules[mn]).__qualname__}"
                f"@{compile_cache.source_hash(sys.modules.get(type(coll._modules[mn]).__module__) or type(coll._modules[mn]))}"
                for mn in cg
            )
            fields = ",".join(
                f"{k}:{jnp.asarray(v).dtype}:{tuple(np.shape(v))}:{m._reductions.get(k)}"
                for k, v in m._defaults.items()
            )
            cfgs = ";".join(
                cfg
                for cfg in (
                    ",".join(map(str, coll._modules[mn]._trace_config())) for mn in cg
                )
                if cfg
            )
            parts.append(f"{name}:[{members}]|{fields}" + (f"|cfg={cfgs}" if cfgs else ""))
        return "Collection{" + ";".join(parts) + "}"

    def _key_desc(self, key: Any) -> str:
        return "|".join(
            (
                compile_cache.toolchain_fingerprint(),
                compile_cache.backend_fingerprint(),
                self._owner_desc(),
                _stable_key_repr(key),
                "donate=0",
            )
        )

    def _state_sig(self) -> Tuple[Any, ...]:
        """Per-leader state shape/dtype signature (see MetricExecutor._state_sig):
        a member whose state layout changes at runtime (laned capacity growth)
        must key a new fused executable, not retrace inside a stale one.
        Memoized per member ``_state_layout_version`` tuple (a handful of
        integer getattrs per call, vs rebuilding every member's shape/dtype
        tuple per dispatch)."""
        vers = tuple(
            getattr(m, "_state_layout_version", 0) for _, m, _ in self._leaders()
        )
        cached = self._state_sig_memo
        if cached is not None and cached[0] == vers:
            return cached[1]
        sig = tuple(
            (
                name,
                ver,
                tuple(
                    (k, tuple(np.shape(v)), str(getattr(v, "dtype", type(v).__name__)))
                    for k, v in m._defaults.items()
                ),
            )
            for ver, (name, m, _) in zip(vers, self._leaders())
        )
        self._state_sig_memo = (vers, sig)
        return sig

    def _clone_owner(self):
        """A fully-detached deep copy of the collection (every member's
        ``__getstate__`` rebuilds its wrapped methods around the copy), with
        all executors disabled, for off-main-thread tracing."""
        import copy

        clone = copy.deepcopy(self._coll)
        clone._executor_enabled = False
        for mm in clone._modules.values():
            mm.__dict__["_executor_enabled"] = False
        return clone

    def _persist_spec(
        self,
        key: Any,
        leader_execs,
        call_leaves: Sequence[Any],
        padded: bool,
        n: Optional[int],
        counts: bool,
        clone_factory: Callable[[Any], Callable],
    ) -> Optional[_PersistSpec]:
        """Collection variant: the donated arg is a dict of per-leader state
        pytrees; fused forward threads a per-leader count dict before the
        batch leaves (matching ``call_fn``'s argument order)."""
        if not all(_is_concrete_array(l) for l in call_leaves):
            return None
        states_sd = {
            name: {k: (tuple(np.shape(v)), jnp.asarray(v).dtype) for k, v in m._defaults.items()}
            for name, m, _, _ in leader_execs
        }
        leaf_sd = [(tuple(np.shape(l)), l.dtype) for l in call_leaves]
        leader_names = tuple(states_sd)
        n_val = int(n) if padded else None
        i32 = jnp.int32

        states_avals = {
            name: {k: jax.ShapeDtypeStruct(s, d) for k, (s, d) in sub.items()} for name, sub in states_sd.items()
        }
        avals: Tuple[Any, ...] = (states_avals,)
        if counts:
            avals += ({name: jax.ShapeDtypeStruct((), i32) for name in leader_names},)
        if padded:
            avals += (jax.ShapeDtypeStruct((), i32),)
        avals += tuple(jax.ShapeDtypeStruct(s, d) for s, d in leaf_sd)

        def dummies() -> Tuple[Any, ...]:
            st = {name: {k: jnp.zeros(s, d) for k, (s, d) in sub.items()} for name, sub in states_sd.items()}
            out: Tuple[Any, ...] = (st,)
            if counts:
                out += ({name: jnp.asarray(0, i32) for name in leader_names},)
            if n_val is not None:
                out += (jnp.asarray(n_val, i32),)
            return out + tuple(_zeros_like_spec(leaf_sd))

        def make_clone_builder() -> Callable[[], Callable]:
            clone = self._clone_owner()
            return lambda: clone_factory(clone)

        return _PersistSpec(self._key_desc(key), avals, dummies, make_clone_builder)

    # ------------------------------------------------------------------ warmup
    def _warmup_bucketable(self) -> bool:
        leader_execs = self._leader_executors()
        return leader_execs is not None and self.bucketable(leader_execs)

    def _warmup_one(self, kind: str, args: tuple, kwargs: dict) -> str:
        if self.disabled_reason is not None:
            return f"{kind}: executor disabled ({self.disabled_reason})"
        leader_execs = self._leader_executors()
        if leader_execs is None:
            return f"{kind}: a compute-group leader is not executor-eligible"
        prep = self._prepare(args, kwargs, leader_execs)
        if prep is None:
            return f"{kind}: inputs not executor-eligible"
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        kw_map = tuple((name, self._kwarg_names(m, kwargs)) for name, m, _ in self._leaders())
        if kind == "update":
            key = ("u", treedef, sig, batched, bucket if padded else None, kw_map, self._state_sig())

            def builder(coll=None):
                specs = [
                    (name, dict(kw_map)[name], {k: jnp.asarray(v) for k, v in m._defaults.items()})
                    for name, m, _ in self._leaders()
                ]
                return self._build_update(treedef, batched, bucket, padded, specs, bool_spec, n_leaves, coll=coll)

            persist = self._persist_spec(key, leader_execs, call_leaves, padded, n, counts=False, clone_factory=builder)
        elif kind == "forward":
            reason = self._forward_unfusable_reason(leader_execs)
            if reason is not None:
                return f"forward: {reason}"
            key = ("f", treedef, sig, batched, bucket if padded else None, kw_map, self._state_sig())

            def builder(coll=None):
                specs = [
                    (name, tuple(cg), dict(kw_map)[name], {k: jnp.asarray(v) for k, v in m._defaults.items()})
                    for name, m, cg in self._leaders()
                ]
                return self._build_forward(treedef, batched, bucket, padded, specs, bool_spec, n_leaves, coll=coll)

            persist = self._persist_spec(key, leader_execs, call_leaves, padded, n, counts=True, clone_factory=builder)
        else:
            return f"{kind}: unknown warmup kind"
        if persist is None:
            return f"{kind}: inputs not persistable (python-scalar leaves)"
        return self._dispatch_warmup(key, builder, persist)

    # --------------------------------------------------------------- builders
    def _build_update(self, treedef, batched, bucket, padded, leader_specs, bool_spec, n_leaves, coll=None):
        # ``coll`` overrides the traced instance: background jobs pass a
        # detached clone so tracing never swaps live member state.
        #
        # Megakernel fusion (ISSUE 11) happens inside this trace for free:
        # every leader's functional_update receives the SAME tracer objects
        # for (args, kwargs), so classification-family leaders sharing a
        # task config resolve their counting core to one shared_result hit
        # (ops/fused_classification.py) — the compiled executable contains a
        # single scatter-accumulate launch serving accuracy + confusion +
        # stat-scores, and the padded-bucket row-0 subtraction below reuses
        # the same shared kernel for its pad oracle.
        coll = coll if coll is not None else self._coll

        def raw(states, *rest):
            from torchmetrics_tpu.ops.kernels import shared_scope

            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn, extra = rest, None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            out = {}
            with shared_scope():  # one megakernel fusion unit per traced step
                for leader, kw_names, defaults in leader_specs:
                    m = coll._modules[leader]
                    fkw = {k: kwargs[k] for k in kw_names}
                    g = m.functional_update(states[leader], *args, **fkw)
                    if extra is not None:
                        rkw = {k: r_kwargs[k] for k in kw_names}
                        g = _subtract_pad_contribution(m, g, defaults, defaults, r_args, rkw, extra)
                    out[leader] = g
            return out

        return raw

    def _build_forward(self, treedef, batched, bucket, padded, leader_specs, bool_spec, n_leaves, coll=None):
        coll = coll if coll is not None else self._coll
        one = jnp.asarray(1, jnp.int32)

        def raw(states, counts, *rest):
            from torchmetrics_tpu.ops.kernels import shared_scope

            if padded:
                n_valid, dyn = rest[0], rest[1:]
                extra = jnp.asarray(bucket, jnp.int32) - n_valid
            else:
                dyn, extra = rest, None
            leaves = _merge_static_bools(dyn, bool_spec, n_leaves)
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            if extra is not None:
                r_args, r_kwargs = jax.tree_util.tree_unflatten(treedef, _row0_leaves(leaves, batched))
            new_states, values = {}, {}
            with shared_scope():  # one megakernel fusion unit per traced step
                for leader, members, kw_names, defaults in leader_specs:
                    m = coll._modules[leader]
                    fkw = {k: kwargs[k] for k in kw_names}
                    bs = m.functional_update(defaults, *args, **fkw)
                    if extra is not None:
                        rkw = {k: r_kwargs[k] for k in kw_names}
                        bs = _subtract_pad_contribution(m, bs, defaults, defaults, r_args, rkw, extra)
                    new_states[leader] = m.merge_states(states[leader], bs, counts=(counts[leader], one))
                    for name in members:
                        values[name] = coll._modules[name].functional_compute(bs)
            return new_states, values

        return raw

    # ----------------------------------------------------------------- shared
    def _prepare(self, args, kwargs, leader_execs):
        leaves, treedef = jax.tree_util.tree_flatten((args, tuple(sorted(kwargs.items()))))
        sig = _classify_leaves(leaves)
        if sig is None:
            return None
        n = _common_batch_dim(leaves)
        bucket, padded = None, False
        if n is not None and n > 0 and self.bucketable(leader_execs):
            bucket = bucket_size(n)
            padded = bucket != n
        if padded:
            with obs.span(obs.SPAN_PAD, n=int(n), bucket=int(bucket)):
                batched = tuple(
                    _is_concrete_array(l) and getattr(l, "ndim", 0) >= 1 and int(l.shape[0]) == n
                    for l in leaves
                )
                call_leaves = _pad_leaves(leaves, batched, bucket)
                sig = _classify_leaves(call_leaves)
        else:
            batched = None
            call_leaves = list(leaves)
        dyn_leaves, bool_spec = _split_static_bools(call_leaves)
        return treedef, dyn_leaves, sig, batched, bucket, n, padded, bool_spec, len(call_leaves)

    def _group_need_copy(self, cg, fresh) -> bool:
        mods = self._coll._modules
        return fresh or any(mods[name]._state_escaped for name in cg)

    def _install(self, leader, new_state, cg, bump_count: bool) -> None:
        mods = self._coll._modules
        m0 = mods[leader]
        object.__setattr__(m0, "_state", dict(new_state))
        if bump_count:
            m0._update_count += 1
            m0._mark_unreduced()  # fresh local accumulation under reduce="deferred"
        m0._computed = None
        for name in cg:
            mm = mods[name]
            mm.__dict__["_state_escaped"] = False
            mm.__dict__["_state_shared"] = True

    # ------------------------------------------------------------------ entry
    def run_update(self, args: tuple, kwargs: dict) -> bool:
        if self.disabled_reason is not None:
            return False
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return False
        leader_execs = self._leader_executors()
        if leader_execs is None:
            return False
        try:
            return self._run_update(args, kwargs, leader_execs)
        except _DispatchFailure as df:
            raise df.original
        except _DiskEntryFailure as df:
            self._evict_disk_entry(df)
            return self.run_update(args, kwargs)
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return False

    def _run_update(self, args, kwargs, leader_execs) -> bool:
        prep = self._prepare(args, kwargs, leader_execs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return False
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        coll = self._coll

        kw_map = tuple((name, self._kwarg_names(m, kwargs)) for name, m, _ in self._leaders())
        key = ("u", treedef, sig, batched, bucket if padded else None, kw_map, self._state_sig())
        self._record_profile(key, "update", args, kwargs)

        def builder(coll=None):
            specs = [
                (name, dict(kw_map)[name], {k: jnp.asarray(v) for k, v in m._defaults.items()})
                for name, m, _ in self._leaders()
            ]
            return self._build_update(treedef, batched, bucket, padded, specs, bool_spec, n_leaves, coll=coll)

        fn, fresh = self._get_fn(
            key,
            builder,
            lambda: self._persist_spec(key, leader_execs, call_leaves, padded, n, counts=False, clone_factory=builder),
        )
        if fn is None:  # compile in flight on the worker: serve this step eagerly
            self.stats["eager_misses"] += 1
            return False

        states, copied = {}, False
        donated = []  # groups whose live buffers go into the donated call
        for name, m, cg, _ in leader_execs:
            st = {k: m._state[k] for k in m._defaults}
            if self._group_need_copy(cg, fresh):
                st = _tree_copy(st)
                copied = True
            else:
                donated.append((name, m, cg, self._take_recovery(m, st, args)))
            states[name] = st

        do_probe = padded and not self._pad_validated
        oracle = None
        if do_probe:
            oracle = {
                name: m.functional_update({k: m._state[k] for k in m._defaults}, *args, **m._filter_kwargs(**kwargs))
                for name, m, _, _ in leader_execs
            }

        def call_fn(states_arg):
            if padded:
                return fn(states_arg, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(states_arg, *call_leaves)

        def copied_states():
            return {
                name: _tree_copy({k: m._state[k] for k in m._defaults})
                for name, m, _, _ in leader_execs
            }

        t_cold_ns = time.perf_counter_ns() if fresh else None
        with obs.span(obs.SPAN_DISPATCH, suffix=self._owner_name(), histogram="executor.dispatch_us", cold=fresh):
            new_states = self._guarded_dispatch(
                lambda: call_fn(states),
                lambda: call_fn(copied_states()),
                fresh,
                lambda: self._restore_groups(donated),
            )
        if t_cold_ns is not None:
            t_now_ns = time.perf_counter_ns()
            self.stats["compile_us_total"] += (t_now_ns - t_cold_ns) / 1e3
            # the cold dispatch IS the foreground compile: give it its own
            # span so a Perfetto trace separates compile stalls from warm steps
            obs.record_span(obs.SPAN_COMPILE, t_cold_ns, t_now_ns, {"owner": self._owner_name()})
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            ok = all(
                _states_close(new_states[name], oracle[name], m._defaults)
                for name, m, _, _ in leader_execs
            )
            if ok:
                self._pad_validated = True
            else:
                # see MetricExecutor._run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_update(args, kwargs, leader_execs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if copied else "donated_calls"] += 1
        for name, _, cg, _ in leader_execs:
            self._install(name, new_states[name], cg, bump_count=True)
        self._cache_collection_recovery(donated, leader_execs)
        # slab-aware dispatch seam: see MetricExecutor._run_update — the fused
        # collection dispatch retires the router's staging slab the same way
        _ingest_notify(new_states)
        return True

    def run_forward(self, args: tuple, kwargs: dict) -> Optional[Dict[str, Any]]:
        """Fused forward for the WHOLE collection, or None to fall back.

        Only engages when every group qualifies for the reduce-merge forward
        (all members ``full_state_update=False``, no ``dist_sync_on_step``)."""
        if self.disabled_reason is not None:
            return None
        if not _trace_clean():
            self.stats["skipped_calls"] += 1
            return None
        leader_execs = self._leader_executors()
        if leader_execs is None:
            return None
        if self._forward_unfusable_reason(leader_execs) is not None:
            return None
        try:
            return self._run_forward(args, kwargs, leader_execs)
        except _DispatchFailure as df:
            raise df.original
        except _DiskEntryFailure as df:
            self._evict_disk_entry(df)
            return self.run_forward(args, kwargs)
        except DispatchStallError:
            raise  # a stalled compile/dispatch must surface, never silently disable
        except Exception as err:
            self._disable(f"{type(err).__name__}: {err}")
            return None

    def _run_forward(self, args, kwargs, leader_execs):
        prep = self._prepare(args, kwargs, leader_execs)
        if prep is None:
            self.stats["skipped_calls"] += 1
            return None
        treedef, call_leaves, sig, batched, bucket, n, padded, bool_spec, n_leaves = prep
        coll = self._coll

        kw_map = tuple((name, self._kwarg_names(m, kwargs)) for name, m, _ in self._leaders())
        key = ("f", treedef, sig, batched, bucket if padded else None, kw_map, self._state_sig())
        self._record_profile(key, "forward", args, kwargs)

        def builder(coll=None):
            specs = [
                (
                    name,
                    tuple(cg),
                    dict(kw_map)[name],
                    {k: jnp.asarray(v) for k, v in m._defaults.items()},
                )
                for name, m, cg in self._leaders()
            ]
            return self._build_forward(treedef, batched, bucket, padded, specs, bool_spec, n_leaves, coll=coll)

        fn, fresh = self._get_fn(
            key,
            builder,
            lambda: self._persist_spec(key, leader_execs, call_leaves, padded, n, counts=True, clone_factory=builder),
        )
        if fn is None:  # compile in flight on the worker: serve this step eagerly
            self.stats["eager_misses"] += 1
            return None

        states, copied = {}, False
        donated = []  # groups whose live buffers go into the donated call
        counts = {}
        for name, m, cg, _ in leader_execs:
            st = {k: m._state[k] for k in m._defaults}
            if self._group_need_copy(cg, fresh):
                st = _tree_copy(st)
                copied = True
            else:
                donated.append((name, m, cg, self._take_recovery(m, st, args)))
            states[name] = st
            counts[name] = jnp.asarray(int(m._update_count), jnp.int32)

        do_probe = padded and not self._pad_validated
        oracle = None
        if do_probe:
            oracle_states, oracle_values = {}, {}
            for name, m, cg, _ in leader_execs:
                bs = m.functional_update(m.functional_init(), *args, **m._filter_kwargs(**kwargs))
                oracle_states[name] = m.merge_states(
                    {k: m._state[k] for k in m._defaults}, bs, counts=(int(m._update_count), 1)
                )
                for member in cg:
                    oracle_values[member] = coll._modules[member].functional_compute(bs)
            oracle = (oracle_states, oracle_values)

        def call_fn(states_arg):
            if padded:
                return fn(states_arg, counts, jnp.asarray(n, jnp.int32), *call_leaves)
            return fn(states_arg, counts, *call_leaves)

        def copied_states():
            return {
                name: _tree_copy({k: m._state[k] for k in m._defaults})
                for name, m, _, _ in leader_execs
            }

        t_cold_ns = time.perf_counter_ns() if fresh else None
        with obs.span(obs.SPAN_DISPATCH, suffix=self._owner_name(), histogram="executor.dispatch_us", cold=fresh):
            new_states, values = self._guarded_dispatch(
                lambda: call_fn(states),
                lambda: call_fn(copied_states()),
                fresh,
                lambda: self._restore_groups(donated),
            )
        if t_cold_ns is not None:
            t_now_ns = time.perf_counter_ns()
            self.stats["compile_us_total"] += (t_now_ns - t_cold_ns) / 1e3
            # the cold dispatch IS the foreground compile: give it its own
            # span so a Perfetto trace separates compile stalls from warm steps
            obs.record_span(obs.SPAN_COMPILE, t_cold_ns, t_now_ns, {"owner": self._owner_name()})
        if padded:
            self.stats["padded_calls"] += 1

        if do_probe:
            self.stats["probes"] += 1
            ok = all(
                _states_close(new_states[name], oracle[0][name], m._defaults)
                for name, m, _, _ in leader_execs
            ) and _values_close(values, oracle[1])
            if ok:
                self._pad_validated = True
            else:
                # see MetricExecutor._run_update: discard and re-dispatch unpadded
                self._bucketing_ok = False
                return self._run_forward(args, kwargs, leader_execs)

        self.stats["calls"] += 1
        self.stats["copied_calls" if copied else "donated_calls"] += 1
        for name, _, cg, _ in leader_execs:
            self._install(name, new_states[name], cg, bump_count=True)
        self._cache_collection_recovery(donated, leader_execs)
        return dict(values)


# ---------------------------------------------------------------------------
# synced-path fusion: update -> sync -> compute as ONE computation
# ---------------------------------------------------------------------------

def make_value_packer(example_values: Any):
    """Build (pack, unpack) for a fixed values pytree.

    ``pack`` (trace-safe) concatenates all leaves of a values pytree into one
    flat vector per dtype — an N-metric collection then materialises O(dtypes)
    replicated output buffers per step instead of O(N). ``unpack`` (host-side)
    restores the original pytree from the packed dict.
    """
    leaves, treedef = jax.tree_util.tree_flatten(example_values)
    specs = [(tuple(l.shape), jnp.dtype(l.dtype)) for l in leaves]
    order: Dict[str, List[int]] = {}
    for i, (_, dt) in enumerate(specs):
        order.setdefault(str(dt), []).append(i)

    def pack(tree):
        lv = jax.tree_util.tree_leaves(tree)
        return {
            dt: jnp.concatenate([jnp.ravel(lv[i]) for i in idxs])
            for dt, idxs in order.items()
        }

    def unpack(packed):
        out: List[Any] = [None] * len(specs)
        for dt, idxs in order.items():
            flat = np.asarray(packed[dt])
            off = 0
            for i in idxs:
                shape, _ = specs[i]
                size = int(np.prod(shape)) if shape else 1
                out[i] = flat[off:off + size].reshape(shape)
                off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return pack, unpack


def make_synced_collection_step(
    collection: Any, axis_name: str = "batch", pack_values: bool = True, reduce: str = "step"
):
    """Fused ``(states, *batch) -> (states', packed_values)`` synced step.

    Meant to be wrapped in the caller's ``shard_map``/``jit`` over a mesh
    binding ``axis_name``. One computation runs every compute group's update,
    folds the whole collection's sync collectives into one ``psum`` per
    (reduction, dtype) (via ``MetricCollection.functional_sync``'s cross-group
    leaf fusion), computes every metric from the synced state, and packs the
    computed leaves per dtype. Returns ``(step, unpack)`` where ``unpack``
    (host-side) restores the values dict from the packed output; it is built
    lazily on the first call's structure when ``pack_values`` is True.

    With ``reduce="deferred"`` the per-step collectives disappear entirely and
    the return becomes ``(local_step, reduce_step, unpack)``: ``local_step``
    accumulates into *sharded* state (leading shard axis, spec
    ``collection.sharded_state_spec(axis_name)``) with ZERO collectives, and
    ``reduce_step(states) -> packed_values`` applies every declared
    ``dist_reduce_fx`` exactly once — the read point of the deferred policy
    (docs/SHARDING.md). :func:`make_deferred_collection_step` wraps the pair
    in ``shard_map``/``jit`` (donation intact) for you.
    """
    if reduce == "deferred":
        # the documented 3-tuple; the shadow's fold body is a
        # DeferredCollectionStep-internal surface
        local_step, reduce_step, _fold, unpack = _make_deferred_bodies(
            collection, axis_name, pack_values
        )
        return local_step, reduce_step, unpack
    if reduce != "step":
        raise ValueError(f"reduce must be 'step' or 'deferred', got {reduce!r}")
    box: Dict[str, Any] = {}

    def step(states, *args, **kwargs):
        st = collection.functional_update(states, *args, **kwargs)
        synced = collection.functional_sync(st, axis_name)
        values = collection.functional_compute(synced)
        if pack_values:
            if "pack" not in box:
                box["pack"], box["unpack"] = make_value_packer(values)
            values = box["pack"](values)
        return st, values

    def unpack(packed):
        if not pack_values:
            return packed
        return box["unpack"](packed)

    return step, unpack


def _make_deferred_bodies(collection: Any, axis_name: str, pack_values: bool, baseline_box: Optional[Dict[str, Any]] = None):
    """(local_step, reduce_step, fold_step, unpack) raw bodies for the
    deferred policy; all are meant to run inside the caller's ``shard_map``
    with the state spec from ``collection.sharded_state_spec(axis_name)``.

    ``baseline_box`` (a mutable dict read at TRACE time) may carry a
    ``"baseline"`` canonical pytree from an elastic restore / shard-loss
    recovery (parallel/reshard.py): the read point then merges the carried
    segment with the freshly-folded live value per the declared reductions,
    so continued accumulation after a topology change stays exact."""
    from torchmetrics_tpu.parallel.reshard import merge_folded
    from torchmetrics_tpu.parallel.sync import reshard_local_state, unshard_local_state

    box: Dict[str, Any] = {}

    def local_step(states, *args, **kwargs):
        # purely local accumulation: unshard -> update -> reshard, no collectives
        with obs.device_span(obs.SPAN_UPDATE):
            local = collection.functional_update(unshard_local_state(states), *args, **kwargs)
        return reshard_local_state(local)

    def _merged(states):
        # one fused collective per (reduction, dtype) for the whole collection,
        # then the carried-baseline merge (a trace constant; elastic restores
        # bump the executable key so stale baselines can never be served)
        synced = collection.reduce_sharded_states(states, axis_name)
        baseline = (baseline_box or {}).get("baseline")
        if baseline is None:
            return synced
        return {
            leader: merge_folded(
                baseline[leader], sub, collection._modules[leader]._reductions
            )
            if leader in baseline
            else sub
            for leader, sub in synced.items()
        }

    def reduce_step(states):
        # the single deferred rendezvous, then every member's compute
        values = collection.functional_compute(_merged(states))
        if pack_values:
            if "pack" not in box:
                box["pack"], box["unpack"] = make_value_packer(values)
            values = box["pack"](values)
        return values

    def fold_step(states):
        # the shard shadow's refresh body: the SAME fused rendezvous but
        # returning the reduced (replicated) states instead of computed
        # values — the canonical form the host shadow stores. The baseline
        # merge happens on the pipeline worker (host side), not here, so the
        # executable survives baseline changes.
        return collection.reduce_sharded_states(states, axis_name)

    def unpack(packed):
        if not pack_values:
            return packed
        return box["unpack"](packed)

    return local_step, reduce_step, fold_step, unpack


class DeferredCollectionStep:
    """Compiled deferred-reduction drivers for one collection on one mesh
    (built by :func:`make_deferred_collection_step`; see docs/SHARDING.md).

    State lives *sharded per-device* along the mesh data axis; the hot loop
    pays zero collectives, and every declared ``dist_reduce_fx`` runs exactly
    once at the read point:

    - :meth:`init_states` — fresh sharded states placed on the mesh.
    - :meth:`local_step` — ``(states, *batch) -> states'``: ONE compiled
      dispatch of purely local accumulation, state pytree **donated**.
    - :meth:`local_epoch` — ``(states, *stacked) -> states'``: a whole chunk
      of steps (leading axis = steps) folded into ONE dispatch via
      ``lax.scan``. Because no step carries a rendezvous, devices run the
      entire chunk decoupled — this is the MapReduce shape (DrJAX) that makes
      epoch-style eval loops run at unsynced speed.
    - :meth:`reduce` — ``states -> values``: the separately cached read-point
      executable; one fused collective per (reduction, dtype) for the whole
      collection, then every metric's compute.

    Elastic topology (docs/DURABILITY.md "Elastic restore",
    docs/ROBUSTNESS.md "Shard loss"):

    - :meth:`restore_states` — reinstall a checkpointed stacked state saved
      on ANY shard count: the fold/expand goes through the audited
      ``parallel/reshard.py`` seam; the folded value becomes a carried
      baseline merged at the read point and fresh identity accumulators go
      back on this mesh.
    - :meth:`attach_shadow` — maintain a bounded-lag host shadow of the
      folded reduce (refreshed via the async read pipeline; the step loop
      only pays an async dispatch every ``every_n_steps``), and resolve
      shard loss (:class:`~torchmetrics_tpu.utils.exceptions.ShardLossError`)
      per ``on_shard_loss``: ``"raise"`` propagates, ``"degraded"`` serves
      the shadow as a ``DegradedValue``, ``"restore"`` reinstalls the shadow
      and continues.
    """

    def __init__(self, collection: Any, mesh: Any, axis_name: str, pack_values: bool, batch_specs: Any, donate: bool) -> None:
        self._coll = collection
        self._mesh = mesh
        self._axis = axis_name
        self._batch_specs = batch_specs
        self._donate = donate
        #: carried canonical baseline from an elastic restore / recovery; read
        #: at trace time by the reduce body (key versioned via _baseline_version)
        self._baseline_box: Dict[str, Any] = {}
        self._baseline_version = 0
        self._local_body, self._reduce_body, self._fold_body, self._unpack = _make_deferred_bodies(
            collection, axis_name, pack_values, self._baseline_box
        )
        self._state_spec = collection.sharded_state_spec(axis_name)
        self._compiled: Dict[Any, Callable] = {}
        #: committed local steps (one per batch; epochs add their chunk length)
        #: — the anchor of the shadow's updates_behind staleness contract
        self._steps = 0
        self._shadow: Optional[Any] = None
        self._on_shard_loss = "raise"
        self._recovered_states: Optional[Any] = None
        self._integrity: Optional[Any] = None

    def _b_specs(self, batch):
        from jax.sharding import PartitionSpec as P

        if self._batch_specs is not None:
            return tuple(self._batch_specs)
        return tuple(P(self._axis) for _ in batch)

    def _epoch_specs(self, batch):
        # stacked chunk: leading axis is steps (unsharded), batch dim next
        from jax.sharding import PartitionSpec as P

        if self._batch_specs is not None:
            return tuple(P(None, *sp) for sp in self._batch_specs)
        return tuple(P(None, self._axis) for _ in batch)

    def init_states(self):
        from jax.sharding import NamedSharding

        states = self._coll.init_sharded_states(len(self._mesh.devices.flatten()))
        shardings = jax.tree_util.tree_map(lambda sp: NamedSharding(self._mesh, sp), self._state_spec)
        return jax.device_put(states, shardings)

    def _get(self, key, builder):
        fn = self._compiled.get(key)
        if fn is None:
            fn = builder()
            self._compiled[key] = fn
        return fn

    def local_step(self, states, *batch):
        from torchmetrics_tpu.parallel.sync import shard_map_compat
        from torchmetrics_tpu.utils.exceptions import ShardLossError

        def build():
            mapped = shard_map_compat(
                self._local_body, self._mesh, (self._state_spec,) + self._b_specs(batch), self._state_spec
            )
            return jax.jit(mapped, donate_argnums=0) if self._donate else jax.jit(mapped)

        fn = self._get(("local", len(batch)), build)
        try:
            with obs.span(obs.SPAN_DISPATCH, suffix=type(self._coll).__name__, histogram="executor.dispatch_us"):
                out = fn(states, *batch)
        except ShardLossError as err:
            if self._on_shard_loss != "restore" or self._shadow is None:
                raise obs.flighted(
                    err, domain="shadow", kind="shard_loss",
                    shard=getattr(err, "shard", None), policy=self._on_shard_loss,
                )
            # reinstall the bounded-lag shadow through the reshard seam and
            # re-apply THIS batch on the fresh accumulators: the run lost at
            # most updates_behind steps, never the whole epoch
            fresh = self.recover()
            with obs.span(obs.SPAN_DISPATCH, suffix=type(self._coll).__name__, histogram="executor.dispatch_us"):
                out = fn(fresh, *batch)
        self._steps += 1
        self._tick_shadow(out)
        self._tick_integrity(out)
        return out

    def local_epoch(self, states, *stacked):
        from torchmetrics_tpu.parallel.sync import shard_map_compat, reshard_local_state, unshard_local_state
        from torchmetrics_tpu.utils.exceptions import ShardLossError

        def build():
            def epoch_body(st, *chunk):
                local = unshard_local_state(st)

                def one(carry, batch):
                    return self._coll.functional_update(carry, *batch), None

                with obs.device_span(obs.SPAN_UPDATE):
                    out, _ = jax.lax.scan(one, local, tuple(chunk))
                return reshard_local_state(out)

            mapped = shard_map_compat(
                epoch_body, self._mesh, (self._state_spec,) + self._epoch_specs(stacked), self._state_spec
            )
            return jax.jit(mapped, donate_argnums=0) if self._donate else jax.jit(mapped)

        fn = self._get(("epoch", len(stacked)), build)
        try:
            with obs.span(obs.SPAN_DISPATCH, suffix=type(self._coll).__name__, histogram="executor.dispatch_us"):
                out = fn(states, *stacked)
        except ShardLossError as err:
            if self._on_shard_loss != "restore" or self._shadow is None:
                raise obs.flighted(
                    err, domain="shadow", kind="shard_loss",
                    shard=getattr(err, "shard", None), policy=self._on_shard_loss,
                )
            fresh = self.recover()
            with obs.span(obs.SPAN_DISPATCH, suffix=type(self._coll).__name__, histogram="executor.dispatch_us"):
                out = fn(fresh, *stacked)
        self._steps += int(jnp.shape(stacked[0])[0]) if stacked else 0
        self._tick_shadow(out)
        self._tick_integrity(out)
        return out

    def reduce(self, states):
        from jax.sharding import PartitionSpec as P

        from torchmetrics_tpu.parallel.sync import shard_map_compat
        from torchmetrics_tpu.utils.exceptions import ShardLossError

        def build():
            # values are replicated after the fused collectives; out_specs=P()
            return jax.jit(shard_map_compat(self._reduce_body, self._mesh, (self._state_spec,), P()))

        fn = self._get(("reduce", self._baseline_version), build)
        try:
            with obs.span(obs.SPAN_REDUCE):
                return self._unpack(fn(states))
        except ShardLossError as err:
            return self._serve_shard_loss(err)

    def reduce_async(self, states):
        """Non-blocking :meth:`reduce` (docs/ASYNC.md): the fused read-point
        executable is *dispatched* here — JAX async dispatch enqueues the
        rendezvous + compute without waiting — and a
        :class:`~torchmetrics_tpu.ops.async_read.MetricFuture` resolves to
        the unpacked values once the device work drains, with the ready-wait
        and the host-side unpack on the pipeline worker. The epoch loop can
        keep feeding :meth:`local_step`/:meth:`local_epoch` immediately;
        pass a non-donated ``states`` alias (the reduce executable does not
        donate, so the same states remain live for the next step)."""
        from jax.sharding import PartitionSpec as P

        from torchmetrics_tpu.ops.async_read import get_pipeline, materialize, resolved_future
        from torchmetrics_tpu.parallel.sync import shard_map_compat
        from torchmetrics_tpu.utils.exceptions import ShardLossError

        def build():
            return jax.jit(shard_map_compat(self._reduce_body, self._mesh, (self._state_spec,), P()))

        fn = self._get(("reduce", self._baseline_version), build)
        # the pipeline submit stays INSIDE the submission span so the captured
        # trace context parents the worker-side resolution under it (the
        # submit->resolve flow arrow of docs/OBSERVABILITY.md)
        with obs.span(obs.SPAN_COMPUTE_ASYNC, suffix="DeferredCollectionStep"):
            try:
                packed = fn(states)  # enqueued on the device stream, not awaited
            except ShardLossError as err:
                # shard loss surfaces at dispatch: resolve the future per policy
                # (the caller still gets a future, like every degradation path)
                return resolved_future(
                    self._serve_shard_loss(err), owner="DeferredCollectionStep.reduce"
                )
            return get_pipeline().submit(
                lambda: self._unpack(materialize(packed)), owner="DeferredCollectionStep.reduce"
            )

    # ------------------------------------------------------- elastic topology
    def _fold_fn(self):
        """The shadow's separately compiled fold executable: the same fused
        rendezvous as :meth:`reduce` but returning the reduced (replicated)
        states — the canonical form the host shadow stores. Non-donating, so
        its output buffers are safe against later donating local steps."""
        from jax.sharding import PartitionSpec as P

        from torchmetrics_tpu.parallel.sync import shard_map_compat

        def build():
            out_spec = jax.tree_util.tree_map(lambda _: P(), self._state_spec)
            return jax.jit(shard_map_compat(self._fold_body, self._mesh, (self._state_spec,), out_spec))

        return self._get("shadow_fold", build)

    def _tick_shadow(self, states) -> None:
        """Cadence hook on every committed local step/epoch: when a refresh
        is due, DISPATCH the fold executable (JAX async dispatch — the step
        loop never waits) and hand the fresh buffers to the read-pipeline
        worker for the ready-wait + D2H (docs/ROBUSTNESS.md "Shard loss")."""
        shadow = self._shadow
        if shadow is None or not shadow.due(self._steps):
            return
        folded = self._fold_fn()(states)  # enqueued, not awaited
        shadow.observe(folded, self._steps, baseline=self._baseline_box.get("baseline"))

    def attach_shadow(self, every_n_steps: int = 8, on_shard_loss: str = "degraded"):
        """Maintain a bounded-lag host shadow of the folded reduce and resolve
        :class:`~torchmetrics_tpu.utils.exceptions.ShardLossError` per
        ``on_shard_loss`` (docs/ROBUSTNESS.md "Shard loss" policy table).
        Returns the :class:`~torchmetrics_tpu.parallel.reshard.ShardShadow`.

        Staleness contract: the shadow trails the live accumulation by at
        most ``every_n_steps - 1`` committed steps plus any refresh still in
        flight on the pipeline; a served ``DegradedValue.updates_behind`` is
        anchored on the shadow's step counter at its last completed refresh.
        """
        from torchmetrics_tpu.parallel.reshard import SHARD_LOSS_POLICIES, ShardShadow

        if on_shard_loss not in SHARD_LOSS_POLICIES:
            raise ValueError(
                f"on_shard_loss must be one of {SHARD_LOSS_POLICIES}, got {on_shard_loss!r}"
            )

        def reductions_of():
            return {
                leader: self._coll._modules[leader]._reductions
                for leader in self._coll.state_spec()
            }

        self._shadow = ShardShadow(reductions_of, every_n_steps=every_n_steps)
        self._on_shard_loss = on_shard_loss
        return self._shadow

    def _tick_integrity(self, states) -> None:
        """Cadence hook on every committed local step/epoch: when an audit
        capture is due, ONE jitted dispatch fingerprints every shard of every
        leaf (enqueued, not awaited) and the readback rides the pipeline
        (docs/ROBUSTNESS.md "Silent data corruption")."""
        integrity = self._integrity
        if integrity is None or not integrity.due(self._steps):
            return
        integrity.observe(states, self._steps)

    def attach_integrity(self, every_n_steps: int = 8, on_divergence: str = "raise"):
        """Audit the carried sharded state's bits on a cadence
        (integrity.py): every ``every_n_steps``-th committed step captures
        per-shard fingerprints (``uint32[S, 2]`` per leaf — bytes, not
        state), and :meth:`~torchmetrics_tpu.integrity.DeferredIntegrity.audit`
        verifies the carried states against them while the step count has
        not moved, naming the shard a flip hit. ``on_divergence="restore"``
        reinstalls the attached shard shadow (:meth:`recover`) — attach one
        first. Returns the :class:`~torchmetrics_tpu.integrity.DeferredIntegrity`
        (also exposed as :attr:`integrity`)."""
        from torchmetrics_tpu.integrity import DeferredIntegrity

        self._integrity = DeferredIntegrity(
            self, every_n_steps=every_n_steps, on_divergence=on_divergence
        )
        return self._integrity

    @property
    def integrity(self):
        return self._integrity

    @property
    def shadow(self):
        return self._shadow

    @property
    def steps(self) -> int:
        """Committed local steps since construction (or the last restore)."""
        return self._steps

    @property
    def baseline(self):
        """The carried canonical baseline from an elastic restore/recovery
        (None on the straight-through path)."""
        return self._baseline_box.get("baseline")

    def _set_baseline(self, canonical) -> None:
        self._baseline_box["baseline"] = canonical
        # the reduce executable closes over the baseline as trace constants:
        # a new baseline must never be served by a stale executable
        self._baseline_version += 1

    def restore_states(self, states, step_count: Optional[int] = None, stacked: Optional[bool] = None):
        """Reinstall checkpointed deferred state on THIS mesh, whatever world
        it was saved on (the elastic-restore read path, docs/DURABILITY.md).

        ``states`` is a leader-keyed pytree — either the stacked sharded
        layout a mid-epoch checkpoint carries (auto-detected via the reserved
        ``"_sharded_shards"`` mark; override with ``stacked=``) or an
        already-canonical (folded) value. The fold routes through the audited
        ``parallel/reshard.py`` seam; the canonical value becomes the carried
        baseline merged at every read, and FRESH identity accumulators (per
        each state's declared ``dist_reduce_fx``) are returned, placed on the
        mesh — exact for all five reduction families. ``step_count`` re-anchors
        the staleness clock (default: the count is left where it was)."""
        from torchmetrics_tpu.parallel.reshard import fold_canonical

        canonical: Dict[str, Dict[str, Any]] = {}
        for leader, sub in states.items():
            reds = self._coll._modules[leader]._reductions
            is_stacked = stacked
            if is_stacked is None:
                is_stacked = isinstance(sub, dict) and sub.get(STATE_SHARDS_KEY) is not None
            # a restore REPLACES any previously carried baseline: the snapshot
            # is the whole accumulation (export_canonical folds a live baseline
            # into the checkpoint, so nothing is ever double-counted)
            canonical[leader] = fold_canonical(sub, reds) if is_stacked else {
                k: v for k, v in sub.items() if k not in (STATE_COUNT_KEY, STATE_SHARDS_KEY)
            }
        obs.counter_inc("shards.elastic_restores")
        self._set_baseline(canonical)
        if step_count is not None:
            self._steps = int(step_count)
        if self._shadow is not None:
            self._shadow.seed(canonical, self._steps)
        return self.init_states()

    def export_canonical(self, states, precision: Optional[str] = None):
        """The checkpointable whole-truth of the accumulation: fold the live
        sharded ``states`` and merge the carried baseline (if any) into ONE
        canonical host pytree — what ``save_state(coll, path, states=...)``
        should persist once a baseline exists (saving the raw sharded states
        alone would silently drop the pre-restore segment). A checkpoint
        surface: it blocks on the fold's D2H, so call it at save points, not
        on the step loop.

        ``precision="quantized"`` returns each leader's canonical value in
        the block-quantized WIRE format instead (``parallel.quantized``
        codes + per-block scales, integer fields raw) — the fleet-uplink
        shape: an aggregator ships 4×/2× fewer payload bytes per folded
        delta and decodes with ``parallel.decode_canonical`` before
        ``merge_folded``. The wire format follows each leader's
        ``sync_quant_bits`` / ``sync_quant_block``. Checkpoints should stay
        ``precision=None`` (exact) — quantizing a restore source would bake
        rounding into the accumulation."""
        from torchmetrics_tpu.parallel.quantized import encode_canonical
        from torchmetrics_tpu.parallel.reshard import merge_folded

        if precision not in (None, "exact", "quantized"):
            raise ValueError(f"precision must be None, 'exact' or 'quantized', got {precision!r}")
        folded = self._fold_fn()(states)
        baseline = self._baseline_box.get("baseline")
        out: Dict[str, Dict[str, Any]] = {}
        for leader, sub in folded.items():
            host = {f: np.asarray(v) for f, v in sub.items()}
            if baseline is not None and leader in baseline:
                host = {
                    f: np.asarray(v)
                    for f, v in merge_folded(
                        baseline[leader], host, self._coll._modules[leader]._reductions
                    ).items()
                }
            if precision == "quantized":
                m = self._coll._modules[leader]
                obs.counter_inc("sync.quantized_reduces")
                host = encode_canonical(
                    host,
                    bits=m.__dict__.get("sync_quant_bits", 8),
                    block_size=m.__dict__.get("sync_quant_block", 256),
                )
            out[leader] = host
        return out

    def canonical_reductions(self) -> Dict[str, Dict[str, Any]]:
        """Per-leader reduction maps for the ``export_canonical`` fold — the
        companion a fleet exporter needs to cut per-field deltas and an
        aggregator needs to ``merge_folded`` them (``fleet.deferred_source``
        pairs the two)."""
        return {
            leader: dict(self._coll._modules[leader]._reductions)
            for leader in self._coll._modules
        }

    def export_delta(self, states, baseline=None):
        """Delta-since-baseline export for fleet uplinks: the canonical fold
        (exact, host numpy) plus the per-leader/per-field payload of what
        changed since ``baseline`` (a previous ``export_delta`` canonical).
        ``baseline=None`` means everything is new — the payload IS the
        canonical. Returns ``(canonical, payload)``; ship the payload, keep
        the canonical as the next call's baseline. Wire-mode semantics
        (suffix/add/replace/merge per reduction+dtype) live in
        ``fleet.delta_since``; this is the executor-side seam so a deferred
        collection can feed a :class:`~torchmetrics_tpu.fleet.LeafExporter`
        without re-deriving its fold."""
        from torchmetrics_tpu.fleet.delta import delta_since

        canonical = self.export_canonical(states)
        reductions = self.canonical_reductions()
        payload: Dict[str, Dict[str, Any]] = {}
        for leader, sub in canonical.items():
            prev = baseline.get(leader) if baseline is not None else None
            payload[leader] = delta_since(sub, prev, reductions[leader])
        return canonical, payload

    def recover(self):
        """Reinstall the shadow's last completed refresh as the carried
        baseline and return fresh accumulators on this mesh — the
        ``on_shard_loss="restore"`` action. Raises when no shadow refresh has
        completed yet (nothing to recover from)."""
        snap = None if self._shadow is None else self._shadow.snapshot()
        if snap is None:
            raise RuntimeError(
                "shard-loss recovery requested but no shadow refresh has completed;"
                " attach_shadow() earlier or lower every_n_steps"
            )
        canonical, shadow_steps = snap
        obs.counter_inc("shards.shadow_restores")
        obs.fault_breadcrumb(
            "shard_loss_restore",
            domain="shadow",
            data={"shadow_steps": shadow_steps, "live_steps": self._steps,
                  "updates_behind": max(0, self._steps - shadow_steps)},
        )
        self._set_baseline(canonical)
        self._steps = int(shadow_steps)
        self._shadow.seed(canonical, self._steps)
        fresh = self.init_states()
        self._recovered_states = fresh
        return fresh

    def take_recovered_states(self):
        """Pop the fresh states a read-point recovery installed (None when no
        recovery happened since the last call) — the epoch loop swaps its
        carry for these after a ``reduce()`` came back degraded-restored."""
        out, self._recovered_states = self._recovered_states, None
        return out

    def _serve_shard_loss(self, err):
        """Resolve a ShardLossError at the read point per ``on_shard_loss``."""
        from torchmetrics_tpu.quarantine import DegradedValue

        shadow = self._shadow
        snap = None if shadow is None else shadow.snapshot()
        if self._on_shard_loss == "raise" or snap is None:
            # the flight blob is the shard-loss black box: the last shadow
            # refreshes / dispatches before the loss plus the counter window
            raise obs.flighted(
                err, domain="shadow", kind="shard_loss",
                shard=getattr(err, "shard", None), policy=self._on_shard_loss,
            )
        canonical, shadow_steps = snap
        behind = max(0, self._steps - shadow_steps)
        obs.gauge_set("shards.shadow_age_updates", behind)
        obs.histogram_observe("shards.shadow_staleness_updates", behind)
        obs.counter_inc("shards.degraded_reads")
        obs.fault_breadcrumb(
            "shard_loss_degraded",
            domain="shadow",
            data={
                "shard": getattr(err, "shard", None),
                "policy": self._on_shard_loss,
                "updates_behind": behind,
            },
        )
        if self._on_shard_loss == "restore":
            self.recover()
        # the shadow IS canonical: compute values from it host-side (eager —
        # the mesh just failed us, so no shard_map rendezvous here)
        values = self._coll.functional_compute(
            {k: {f: jnp.asarray(v) for f, v in sub.items()} for k, sub in canonical.items()}
        )
        return DegradedValue(value=values, updates_behind=behind, age_updates=shadow_steps)


def make_deferred_collection_step(
    collection: Any,
    mesh: Any,
    axis_name: str = "batch",
    pack_values: bool = True,
    batch_specs: Any = None,
    donate: bool = True,
) -> DeferredCollectionStep:
    """Compile the deferred-reduction epoch loop for ``collection`` on ``mesh``.

    Returns a :class:`DeferredCollectionStep` whose ``local_step`` (one batch
    per dispatch) and ``local_epoch`` (a stacked chunk of steps per dispatch,
    scanned) accumulate into sharded state with ZERO per-step collectives and
    the state pytree donated; ``reduce`` applies every declared
    ``dist_reduce_fx`` exactly once (one fused rendezvous per
    (reduction, dtype) for the whole collection) — call it at
    compute()/epoch end.

    ``batch_specs`` gives the PartitionSpec(s) of the per-batch arguments
    (default: every argument sharded along ``axis_name`` on its leading dim).
    """
    return DeferredCollectionStep(collection, mesh, axis_name, pack_values, batch_specs, donate)


def latest_recovery_snapshot(obj: Any) -> Optional[Tuple[int, Dict[str, Any]]]:
    """The most recent donating dispatch's host-side recovery snapshot, shaped
    like a ``state()`` export — the Autosaver's free checkpoint source
    (io/checkpoint.py: the forced copy already exists; serializing it costs
    zero extra device sync).

    Returns ``(update_count, export)`` where the export carries the reserved
    ``"_update_count"`` key(s) like a real ``state()`` export, or None when no
    snapshot exists or it is STALE — i.e. not exactly one committed update
    behind the live state (state escaped, eager fallback engaged, recovery
    disabled): a stale snapshot would silently checkpoint old history.
    """
    ex = getattr(obj, "_executor_obj", None)
    rec = getattr(ex, "_last_recovery", None)
    if rec is None:
        return None

    def augment(metric: Any, entry: Dict[str, Any]) -> Dict[str, Any]:
        # wrappers carrying host-side metadata alongside their array states
        # (LanedMetric's session->lane directory) contribute it here so a
        # recovery-reused autosave snapshot restores completely
        extras = getattr(metric, "_export_extras", None)
        if callable(extras):
            entry.update(extras())
        return entry

    def resolve(snap: Any) -> Optional[Dict[str, Any]]:
        # partial (lane-mirror) recoveries are folded forward by later rounds:
        # materialize a detached host copy NOW (host-to-host memcpy, still
        # zero device sync); the count+1 freshness checks below guarantee the
        # mirror still equals the count-committed state
        if hasattr(snap, "materialize"):
            return snap.materialize()
        return snap

    if isinstance(ex, CollectionExecutor):
        coll = ex._coll
        export: Dict[str, Any] = {}
        counts = []
        for leader, (count, snap) in rec.items():
            if int(coll._modules[leader]._update_count) != count + 1:
                return None
            snap = resolve(snap)
            if snap is None:
                return None
            entry = dict(snap)
            entry[STATE_COUNT_KEY] = int(count)
            export[leader] = augment(coll._modules[leader], entry)
            counts.append(int(count))
        if not counts:
            return None
        return max(counts), export
    count, snap = rec
    if int(ex._metric._update_count) != count + 1:
        return None
    snap = resolve(snap)
    if snap is None:
        return None
    export = dict(snap)
    export[STATE_COUNT_KEY] = int(count)
    return int(count), augment(ex._metric, export)


def executor_stats(obj: Any) -> Dict[str, Any]:
    """Executor instrumentation for a ``Metric`` or ``MetricCollection``.

    Returns zeroed stats when the executor has not engaged yet (or is
    disabled); see the keys in this module's ``_new_stats``.
    """
    ex = getattr(obj, "_executor_obj", None)
    if ex is None:
        out = _new_stats()
        out["disabled_reason"] = None
        out["fallback_reason"] = None
        out["bucketing_enabled"] = True
        out["cached_executables"] = 0
        out["background_enabled"] = compile_cache.background_compile_default()
        out["pending_background"] = 0
        out["profile_entries"] = 0
        return out
    return ex.stats_dict()
