"""Fully asynchronous read path: the pipeline behind ``compute_async()``.

Updates are zero-collective (``reduce="deferred"``, docs/SHARDING.md) and
compiles are stall-free (the compile-ahead worker, ops/compile_cache.py), but
a blocking ``compute()``/``sync()`` still serialises the step loop on the
fused reduce plus the device→host transfer — the exact overlap failure the
pjit/TPUv4 dispatch-ahead discipline exists to avoid (PAPERS.md). This module
closes that last hot-path stall:

- **``MetricFuture``** — what ``compute_async()``/``sync_async()`` return: a
  thread-safe future resolving to exactly the value the matching blocking
  call would have produced from the state at submission time (or raising
  exactly the error it would have raised — ``on_sync_failure`` policies,
  :class:`~torchmetrics_tpu.quarantine.DegradedValue` degraded serving and
  all). The resolved value is *ready*: ``block_until_ready`` already ran on
  the worker, so ``float(fut.result())`` costs a host memcpy, never a device
  round-trip.

- **``ReadPipeline``** — one daemon worker thread + bounded queue running the
  blocking tail of every read: wait-for-device (the fused reduce was already
  *dispatched* on the caller thread — JAX async dispatch enqueues it without
  waiting), the bounded multi-host gather when one is due, the host finalize,
  and the D2H materialisation. This is the read-side sibling of the compile
  worker (ops/compile_cache.py): background work layered over a correct
  blocking path, never able to wedge interpreter exit (daemon thread), with
  a full queue degrading to an *inline* (caller-side, blocking) read rather
  than dropping the job — a read produces a value someone is waiting on, so
  unlike a compile it can never be discarded.

Consistency (the double-buffer): the caller-side half of ``compute_async``
snapshots the live state by *reference* — jax arrays are immutable, so the
snapshot is free — and marks the state escaped, which makes the executor's
next donating dispatch copy-before-donate (ops/executor.py ``need_copy``).
The step loop's next ``update()`` therefore writes a fresh buffer while the
in-flight read drains the old one; no second copy path exists (the same
``_state_escaped`` seam the recovery snapshot and ``LaneStateMirror`` already
rely on). Worker-side evaluation runs against a cached detached clone of the
owner, because ``functional_compute`` swaps live ``_state`` during the call —
the same live-object-off-thread race the compile worker learned to avoid.

The blocking-host-sync lint (tools/lint_blocking_host_sync.py) covers this
module: ``block_until_ready``/``np.asarray`` may land ONLY in the worker-side
functions allowlisted there (``materialize``, ``fetch_host``) — the pipeline
worker is the one sanctioned place a read blocks.

See docs/ASYNC.md for the full API and staleness contract.
"""
from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from torchmetrics_tpu.utils.prints import rank_zero_debug

__all__ = [
    "MetricFuture",
    "ReadPipeline",
    "get_pipeline",
    "drain_pipeline",
    "pending_reads",
]

#: bounded depth of the read queue; a full queue degrades the submitting call
#: to an inline (blocking) read instead of stalling or dropping
QUEUE_MAXSIZE_ENV = "TORCHMETRICS_TPU_READ_QUEUE"
DEFAULT_QUEUE_MAXSIZE = 256


class MetricFuture:
    """Handle to one in-flight asynchronous read.

    Resolves to exactly what the matching blocking call would have returned
    for the state at submission time — including a
    :class:`~torchmetrics_tpu.quarantine.DegradedValue` under degraded-read
    policies — or raises exactly the error the blocking call would have
    raised (``result()`` re-raises it; ``exception()`` returns it).
    """

    def __init__(self, owner: str = "", submitted_count: Optional[int] = None) -> None:
        self.owner = owner
        #: the owner's committed update count at submission — the value this
        #: future resolves to reflects exactly this many updates
        self.submitted_count = submitted_count
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._callbacks: list = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------- consumers
    def done(self) -> bool:
        """True once the read resolved (value or error) — never blocks."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until resolved (or ``timeout`` seconds); True when done."""
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Any:
        """The read's value; blocks until resolved. Raises the read's error
        if it failed, or ``TimeoutError`` when ``timeout`` expires first."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"asynchronous read of {self.owner or 'metric'} did not resolve within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._value

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        """The error the read failed with (None on success); blocks like
        :meth:`result`."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"asynchronous read of {self.owner or 'metric'} did not resolve within {timeout}s"
            )
        return self._error

    @property
    def degraded(self) -> bool:
        """True when the resolved value is a
        :class:`~torchmetrics_tpu.quarantine.DegradedValue` (requires the
        future to be done; False while pending)."""
        from torchmetrics_tpu.quarantine import DegradedValue

        return self.done() and self._error is None and isinstance(self._value, DegradedValue)

    def add_done_callback(self, fn: Callable[["MetricFuture"], None]) -> None:
        """Run ``fn(future)`` when the read resolves (immediately if it
        already has). Callbacks run on the pipeline worker thread; exceptions
        out of them are swallowed (a monitoring hook must not kill reads)."""
        run_now = False
        with self._lock:
            if self._event.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            try:
                fn(self)
            except Exception as err:
                rank_zero_debug(f"MetricFuture done-callback failed: {type(err).__name__}: {err}")

    # -------------------------------------------------------------- producer
    def _finish(self, value: Any, error: Optional[BaseException]) -> None:
        with self._lock:
            self._value = value
            self._error = error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception as err:
                rank_zero_debug(f"MetricFuture done-callback failed: {type(err).__name__}: {err}")

    def __repr__(self) -> str:
        state = "pending"
        if self.done():
            state = "error" if self._error is not None else ("degraded" if self.degraded else "done")
        return f"MetricFuture(owner={self.owner!r}, {state})"


def resolved_future(value: Any, owner: str = "", submitted_count: Optional[int] = None) -> MetricFuture:
    """An already-done future (the inline-read degradation path)."""
    fut = MetricFuture(owner=owner, submitted_count=submitted_count)
    fut._finish(value, None)
    return fut


# ------------------------------------------------------- worker-side blocking

def materialize(value: Any) -> Any:
    """WORKER-SIDE ONLY: wait until every array in ``value`` is ready.

    The sanctioned blocking point of the read pipeline (allowlisted in
    tools/lint_blocking_host_sync.py): after this, converting any leaf to
    host (``float``, ``np.asarray``) is a memcpy, not a device round-trip.
    Returns ``value`` unchanged (jax arrays stay jax arrays — ready ones)."""
    try:
        return jax.block_until_ready(value)
    except (TypeError, ValueError):
        # pytrees carrying non-blockable leaves (None, python scalars, host
        # objects): block leaf-wise, skipping anything without device buffers
        def _ready_leaf(x: Any) -> Any:
            if hasattr(x, "block_until_ready"):
                x.block_until_ready()
            return x

        return jax.tree_util.tree_map(_ready_leaf, value)


def fetch_host(value: Any) -> np.ndarray:
    """WORKER-SIDE ONLY: one array's device→host fetch (allowlisted). The
    laned health scan feeds through here so lanes.py itself stays clean of
    worker-side blocking calls."""
    return np.asarray(value)


# ---------------------------------------------------------------- the worker

class ReadPipeline:
    """One daemon thread + bounded queue draining asynchronous reads.

    ``submit`` is non-blocking: a full queue runs the job INLINE on the
    calling thread (counted — the caller momentarily pays blocking-read cost,
    the documented backpressure mode) because a read, unlike a background
    compile, produces a value its future's holder is waiting on. Jobs run in
    submission order on a single worker, so per-metric read clones are used
    serially by construction."""

    def __init__(self, maxsize: Optional[int] = None) -> None:
        if maxsize is None:
            try:
                maxsize = int(os.environ.get(QUEUE_MAXSIZE_ENV, "") or DEFAULT_QUEUE_MAXSIZE)
            except ValueError:
                maxsize = DEFAULT_QUEUE_MAXSIZE
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, maxsize))
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "submitted": 0,
            "completed": 0,
            "errors": 0,
            "degraded": 0,
            "inline": 0,
        }

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="tm_tpu_read_pipeline", daemon=True
                )
                self._thread.start()

    def _execute(
        self,
        job: Callable[[], Any],
        fut: MetricFuture,
        ctx: Any = None,
        t_submit_ns: int = 0,
    ) -> None:
        """Run one read job: the worker-side half of the causal trace.

        The submission-side :class:`~torchmetrics_tpu.obs.TraceContext` is
        reopened here (``obs.use_context``) so the ``tm_tpu.read.resolve``
        span — and every span the job itself opens (reduce, sync, checkpoint
        write) — carries the submitter's ``trace_id`` with a flow-event pair
        back to the submitting slice. Queue-wait and end-to-end latency land
        in the registry histograms (``t_submit_ns`` is 0 when telemetry was
        off at submission — then nothing is observed)."""
        from torchmetrics_tpu import obs
        from torchmetrics_tpu.quarantine import DegradedValue

        if t_submit_ns:
            obs.histogram_observe(
                "reads.queue_wait_us", (time.perf_counter_ns() - t_submit_ns) / 1e3
            )
        with obs.use_context(ctx):
            try:
                # the span wraps the job so an error inside it lands on the
                # span's error attr AND the read domain's flight ring
                with obs.span(obs.SPAN_READ_RESOLVE, suffix=fut.owner or None):
                    value = job()
            except BaseException as err:  # the future carries it to result()
                self.stats["errors"] += 1
                obs.counter_inc("reads.async_errors")
                rank_zero_debug(
                    f"async read of {fut.owner or 'metric'} failed: {type(err).__name__}: {err}"
                )
                fut._finish(None, err)
                if t_submit_ns:
                    obs.histogram_observe(
                        "reads.e2e_latency_us", (time.perf_counter_ns() - t_submit_ns) / 1e3
                    )
                return
        self.stats["completed"] += 1
        if isinstance(value, DegradedValue):
            self.stats["degraded"] += 1
            obs.counter_inc("reads.async_degraded")
            obs.histogram_observe("reads.staleness_age_updates", value.updates_behind)
        obs.counter_inc("reads.async_completed")
        fut._finish(value, None)
        if t_submit_ns:
            obs.histogram_observe(
                "reads.e2e_latency_us", (time.perf_counter_ns() - t_submit_ns) / 1e3
            )

    def _run(self) -> None:
        from torchmetrics_tpu import obs

        while True:
            job, fut, ctx, t_submit_ns = self._q.get()
            try:
                self._execute(job, fut, ctx, t_submit_ns)
            finally:
                self._q.task_done()
                obs.gauge_set("reads.pending", self._q.unfinished_tasks)

    def submit(self, job: Callable[[], Any], owner: str = "", submitted_count: Optional[int] = None) -> MetricFuture:
        """Enqueue one read; returns its future immediately. Never blocks on
        the queue: when full, the job runs inline (blocking THIS call, which
        is the documented backpressure degradation, not a stall bug). The
        ambient trace context is captured here and reopened on the worker, so
        the submitting span and the worker-side replay share one trace id —
        capture is a thread-local read, zero-cost when tracing is off."""
        from torchmetrics_tpu import obs

        fut = MetricFuture(owner=owner, submitted_count=submitted_count)
        ctx = obs.capture_context()
        t_submit_ns = time.perf_counter_ns() if obs.telemetry_enabled() else 0
        self.stats["submitted"] += 1
        obs.counter_inc("reads.async_submitted")
        try:
            self._q.put_nowait((job, fut, ctx, t_submit_ns))
        except queue.Full:
            self.stats["inline"] += 1
            obs.counter_inc("reads.inline_fallback")
            self._execute(job, fut, ctx, t_submit_ns)
            return fut
        obs.gauge_set("reads.pending", self._q.unfinished_tasks)
        self._ensure_thread()
        return fut

    def pending(self) -> int:
        return self._q.unfinished_tasks

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted read resolved; True when the queue
        drained within ``timeout`` (tests, benchmarks, shutdown flushes)."""
        import time

        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True


_PIPELINE: Optional[ReadPipeline] = None
_PIPELINE_LOCK = threading.Lock()


def get_pipeline() -> ReadPipeline:
    """The process-wide read pipeline (created on first use)."""
    global _PIPELINE
    with _PIPELINE_LOCK:
        if _PIPELINE is None:
            _PIPELINE = ReadPipeline()
        return _PIPELINE


def drain_pipeline(timeout: float = 60.0) -> bool:
    """Wait for all in-flight asynchronous reads (no-op when none started)."""
    with _PIPELINE_LOCK:
        pipeline = _PIPELINE
    return True if pipeline is None else pipeline.drain(timeout)


def pending_reads() -> int:
    """Reads submitted but not yet resolved, process-wide."""
    with _PIPELINE_LOCK:
        pipeline = _PIPELINE
    return 0 if pipeline is None else pipeline.pending()


# -------------------------------------------------- laned read serialisation

#: one RLock per LaneGuard (shared across a LanedCollection's members exactly
#: the way the guard itself is): the pipeline worker's scan-and-attribute
#: critical section and the router's guard/state mutations serialise on it.
#: Held only around HOST-side bookkeeping — never around device work or D2H —
#: so the step loop can wait microseconds on it, not milliseconds. Keyed
#: weakly so guards stay picklable (a lock never rides a checkpoint).
_GUARD_LOCKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_GUARD_LOCKS_LOCK = threading.Lock()


def guard_lock(guard: Any) -> threading.RLock:
    """The (lazily created) RLock serialising reads/mutations for ``guard``."""
    with _GUARD_LOCKS_LOCK:
        lock = _GUARD_LOCKS.get(guard)
        if lock is None:
            lock = threading.RLock()
            _GUARD_LOCKS[guard] = lock
        return lock
