"""Zero-copy pipelined lane ingest: the staging-slab ring behind the router.

The lane router is the last host-bound stage of the hot path: every round
used to pay a fresh ``np.stack`` alloc+copy per argument plus one synchronous
H2D upload before the donated dispatch could go out
(``lanes.py _stack_rows``), so at production event rates the single host core
— not the device — capped sessions/s. This module applies the pjit/TPUv4
dispatch-ahead discipline (PAPERS.md: always have the next step's host work
hidden under the current step's device work) to metric ingest:

- **Staging slabs** (:class:`StagingSlab`) — per-``(bucket, arg-layout)``
  preallocated host buffers reused round-over-round. Router rows are written
  *in place* into the slab (no per-round stack allocation), the PR 8
  vectorized admission screen runs against the slab region directly
  (:func:`quarantine.screen_slab_leaf`), and the lane-id vector rides the
  same buffer. Layout deviants (ragged rows, dtype drift, garbage) fall back
  to the legacy ``_stack_rows``/``_stack_rows_screened`` path bit-for-bit —
  the slab fast path only ever serves the uniform round.

- **The slab ring** (:class:`SlabRing`) — a bounded ring of slabs per layout.
  A slab checked out for round k is only handed out again once its *retire
  tokens* — the device arrays uploaded from it, plus (via the executor's
  slab-aware dispatch seam, ``ops/executor.py _ingest_notify``) a leaf of the
  state the consuming dispatch committed — report ready. A donated dispatch
  can therefore never observe a slab being overwritten for the next round:
  the committed-state token is only ready once the computation that consumed
  the uploads finished, which covers BOTH transfer-in-flight (``device_put``
  copying semantics) and the zero-copy case where the backend decides
  PER-ARRAY (by alignment) to alias host memory instead of copying. Any path
  that cannot produce the committed-state token — a dispatch death, an eager
  fallback that bypassed the executor — :meth:`~SlabRing.discard`\\ s the slab
  instead of ever reusing it, and :func:`device_put_aliases_host` (a one-shot
  probe) additionally forces defensive upload copies on backends that alias
  globally.

- **The pack pipeline** (:class:`IngestPipeline`) — one bounded single-worker
  thread (the same shape as ``ReadPipeline``/``CompileWorker``) that screens
  and packs round k+1 into the next slab while round k's H2D and donated
  dispatch are still in flight. Backpressure (full queue, busy ring, layout
  deviants, worker death) degrades to the router's inline pack — a round can
  never be dropped or reordered, because the router consumes pack tickets
  strictly in submission order and packs inline whenever no ticket exists.

Observability (inherits the PR 13 substrate): pack submission captures the
ambient :class:`~torchmetrics_tpu.obs.TraceContext` and the worker reopens it,
so pack→dispatch renders as Perfetto flow arrows; pack durations land in the
``lanes.pack_us`` histogram; ``lanes.pipelined_rounds`` / ``lanes.inline_packs``
/ ``lanes.h2d_bytes`` counters track the split; worker faults route through
``obs.flighted`` into the ``lanes`` flight domain.

Blocking-host-sync lint: this module is a HOT_PATH_FILES member. The only
blocking calls live in the documented worker-side allowlist entries
(``_wait_tokens`` — the pack worker's retire wait; ``_probe_alias`` — the
one-shot import-time semantics probe on a 16-byte scratch array).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.prints import rank_zero_debug

__all__ = [
    "IngestPipeline",
    "PackResult",
    "PackTicket",
    "SlabRing",
    "SlabSpec",
    "StagingSlab",
    "device_put_aliases_host",
    "dispatch_scope",
    "drain_pipeline",
    "get_pipeline",
    "get_ring",
    "notify_dispatched",
    "pack_async",
    "pack_inline",
    "pipeline_enabled",
    "reset_for_tests",
    "stamp_and_upload",
]

#: pipeline master switch (the inline pack is the degraded mode, not a
#: different semantics — parity is the contract either way)
PIPELINE_ENV = "TORCHMETRICS_TPU_INGEST_PIPELINE"
#: slabs per (bucket, layout) ring entry; depth 1 still works (the acquire
#: waits for retirement), depth >=2 hides the wait
RING_DEPTH_ENV = "TORCHMETRICS_TPU_INGEST_RING"
DEFAULT_RING_DEPTH = 4
#: bounded pack-queue depth; a full queue degrades the submit to inline
QUEUE_ENV = "TORCHMETRICS_TPU_INGEST_QUEUE"
DEFAULT_QUEUE_MAXSIZE = 2
#: distinct (bucket, layout) ring entries kept before the least-recently-used
#: one is dropped (its in-flight slabs stay alive via their own references)
MAX_SPECS = 8


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).strip().lower() not in ("0", "false", "off", "no")


def pipeline_enabled() -> bool:
    """Whether the staged pack pipeline may engage (env master switch)."""
    return _env_on(PIPELINE_ENV, "1")


def _ring_depth() -> int:
    try:
        depth = int(os.environ.get(RING_DEPTH_ENV, "") or DEFAULT_RING_DEPTH)
    except ValueError:
        depth = DEFAULT_RING_DEPTH
    return max(1, depth)


# --------------------------------------------------------------- alias probe

_ALIAS_PROBE: Optional[bool] = None


def _probe_alias() -> bool:
    """ONE-SHOT probe of this backend's ``device_put`` host-buffer semantics:
    mutate a 16-byte scratch array after upload and read the device copy back.
    The ``np.asarray`` here is the deliberate probe read — it runs once per
    process on a scratch array, never on traffic."""
    scratch = np.zeros((4,), np.float32)
    try:
        dev = jnp.asarray(scratch)
        scratch[:] = 1.0
        return bool(np.asarray(dev)[0] == 1.0)
    except Exception as err:  # an unprobeable backend is treated as aliasing (safe)
        rank_zero_debug(f"ingest: device_put alias probe failed ({type(err).__name__}: {err})")
        return True


def device_put_aliases_host() -> bool:
    """True when ``jnp.asarray`` of a host array may alias its memory instead
    of copying (zero-copy PJRT semantics). Aliasing backends get the
    defensive per-upload copy so slab reuse can never corrupt an in-flight
    dispatch; copying backends upload straight from the slab."""
    global _ALIAS_PROBE
    if _ALIAS_PROBE is None:
        _ALIAS_PROBE = _probe_alias()
    return _ALIAS_PROBE


# ------------------------------------------------------------------ the slab


class SlabSpec(NamedTuple):
    """The (bucket, per-arg layout) identity of one slab shape."""

    bucket: int
    leaves: Tuple[Tuple[Tuple[int, ...], str], ...]  # per-arg (row shape, dtype str)


class _SlabFallback(Exception):
    """Internal: the round deviates from the slab fast-path layout — the
    router must run the legacy inline pack (exact parity path)."""


def make_spec(batches: Sequence[Tuple[Any, ...]], bucket: int) -> Optional[SlabSpec]:
    """Derive the round's slab layout from its first row; None when the round
    cannot take the slab fast path (un-arrayable leaves, non-numeric dtypes).
    Per-row conformance is checked during the in-place write — this only
    reads ONE row."""
    if not batches:
        return None
    first = batches[0]
    leaves = []
    try:
        for leaf in first:
            arr = np.asarray(leaf)
            if arr.dtype.kind not in "fiub" or arr.dtype.hasobject:
                return None
            leaves.append((tuple(arr.shape), arr.dtype.str))
    except Exception as err:  # un-arrayable first row: the legacy pack owns it
        rank_zero_debug(f"ingest: round cannot take the slab path ({type(err).__name__}: {err})")
        return None
    return SlabSpec(int(bucket), tuple(leaves))


class StagingSlab:
    """One preallocated pack target: per-arg ``(bucket, *row)`` host buffers
    plus the lane-id vector riding the same object. Reused round-over-round;
    the ring hands it out only once its retire tokens report ready."""

    __slots__ = ("spec", "args", "lane_ids", "tokens", "generation", "busy", "_upload")

    def __init__(self, spec: SlabSpec) -> None:
        self.spec = spec
        self.args: List[np.ndarray] = [
            np.zeros((spec.bucket,) + shape, dtype=np.dtype(dt)) for shape, dt in spec.leaves
        ]
        self.lane_ids = np.zeros((spec.bucket,), np.int32)
        #: device arrays that must be ready before the buffers may be reused
        self.tokens: Tuple[Any, ...] = ()
        #: bumped on every acquire — tests use it to prove reuse (not realloc)
        self.generation = 0
        #: checked out (being packed / awaiting dispatch) — not reacquirable
        self.busy = False
        self._upload: Tuple[Any, ...] = ()

    def nbytes(self) -> int:
        return int(sum(a.nbytes for a in self.args) + self.lane_ids.nbytes)


def _token_done(t: Any) -> Optional[bool]:
    """Fast non-blocking verdict for one retire token: True (provably done),
    False (still pending), None (cannot tell without blocking). A DELETED
    array — its buffer donated into a LATER dispatch — proves the consuming
    computation finished long ago, so deletion counts as done."""
    deleted = getattr(t, "is_deleted", None)
    if deleted is not None:
        try:
            if deleted():
                return True
        except Exception as err:
            rank_zero_debug(f"ingest: token deletion probe failed ({type(err).__name__}: {err})")
            return None
    ready = getattr(t, "is_ready", None)
    if ready is None:
        return None
    try:
        return bool(ready())
    except Exception as err:  # racing deletion between the two probes
        rank_zero_debug(f"ingest: token readiness probe failed ({type(err).__name__}: {err})")
        return None


def _tokens_ready(tokens: Tuple[Any, ...]) -> bool:
    """Non-blocking retire check (the inline path's acquire gate)."""
    return all(_token_done(t) is True for t in tokens)


def _wait_tokens(tokens: Tuple[Any, ...]) -> None:
    """WORKER-SIDE retire wait (allowlisted): block until every token — the
    slab's uploaded input arrays plus the consuming dispatch's committed
    state leaf — is ready, so overwriting the slab cannot race an in-flight
    transfer or (on aliasing backends) the dispatch itself. A token whose
    buffer was donated into a LATER dispatch is already proof of completion
    (:func:`_token_done`)."""
    for t in tokens:
        if _token_done(t) is True:
            continue
        try:
            jax.block_until_ready(t)
        except Exception:  # deleted mid-wait: completion already proven
            rank_zero_debug("ingest: retire token deleted mid-wait; completion already proven")


class SlabRing:
    """Bounded ring of :class:`StagingSlab` per layout, LRU across layouts."""

    def __init__(self, depth: Optional[int] = None) -> None:
        self._depth = depth if depth is not None else _ring_depth()
        self._lock = threading.Lock()
        self._slabs: Dict[SlabSpec, List[StagingSlab]] = {}
        self._cursor: Dict[SlabSpec, int] = {}
        self._touch: Dict[SlabSpec, int] = {}
        self._clock = 0
        self.stats: Dict[str, int] = {"allocated": 0, "reused": 0, "busy": 0, "discarded": 0}

    def _entry(self, spec: SlabSpec) -> List[StagingSlab]:
        slabs = self._slabs.get(spec)
        if slabs is None:
            if len(self._slabs) >= MAX_SPECS:
                oldest = min(self._touch, key=self._touch.get)
                del self._slabs[oldest], self._cursor[oldest], self._touch[oldest]
            slabs = []
            self._slabs[spec] = slabs
            self._cursor[spec] = 0
        self._clock += 1
        self._touch[spec] = self._clock
        return slabs

    def _try_acquire(self, spec: SlabSpec, allow_unretired: bool):
        """One locked pass: (slab, wait_tokens). A busy slab (checked out,
        still being packed or awaiting dispatch) is never handed out twice."""
        with self._lock:
            slabs = self._entry(spec)
            n = len(slabs)
            for i in range(n):
                slab = slabs[(self._cursor[spec] + i) % n]
                if slab.busy:
                    continue
                if not slab.tokens or _tokens_ready(slab.tokens):
                    self._cursor[spec] = (self._cursor[spec] + i + 1) % n
                    slab.busy = True
                    slab.tokens = ()
                    slab._upload = ()
                    slab.generation += 1
                    self.stats["reused" if slab.generation > 1 else "allocated"] += 1
                    return slab, ()
            if n < self._depth:
                slab = StagingSlab(spec)
                slabs.append(slab)
                slab.busy = True
                slab.generation = 1
                self.stats["allocated"] += 1
                return slab, ()
            if not allow_unretired:
                return None, ()
            for i in range(n):  # oldest non-busy slab, unretired: caller waits
                slab = slabs[(self._cursor[spec] + i) % n]
                if slab.busy:
                    continue
                self._cursor[spec] = (self._cursor[spec] + i + 1) % n
                tokens, slab.tokens, slab._upload = slab.tokens, (), ()
                slab.busy = True
                slab.generation += 1
                self.stats["reused"] += 1
                return slab, tokens
            return None, ()

    def acquire(self, spec: SlabSpec, block: bool, timeout: float = 30.0) -> Optional[StagingSlab]:
        """The next reusable slab for ``spec``. Non-blocking (``block=False``,
        the router's inline path): None when every slab is still in flight —
        the caller degrades to the legacy pack. Blocking (``block=True``, the
        pack WORKER only): waits for the oldest slab's retire tokens."""
        slab, tokens = self._try_acquire(spec, allow_unretired=block)
        if slab is not None:
            if tokens:
                _wait_tokens(tokens)  # outside the lock: the ring stays concurrent
            return slab
        if not block:
            self.stats["busy"] += 1
            return None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:  # every slab checked out: rare
            time.sleep(0.0005)
            slab, tokens = self._try_acquire(spec, allow_unretired=True)
            if slab is not None:
                if tokens:
                    _wait_tokens(tokens)
                return slab
        self.stats["busy"] += 1
        return None

    def commit(self, slab: StagingSlab, tokens: Tuple[Any, ...]) -> None:
        """Mark ``slab`` in flight behind ``tokens`` (checked at reacquire)."""
        slab.tokens = tuple(tokens)
        slab.busy = False

    def release(self, slab: StagingSlab) -> None:
        """Return an acquired slab unused (its round diverted entirely)."""
        slab.tokens = ()
        slab._upload = ()
        slab.busy = False

    def discard(self, slab: StagingSlab) -> None:
        """Drop a slab whose consumption cannot be proven (fault path): it is
        never reused — in-flight readers keep it alive via their own refs and
        the ring replaces it lazily."""
        slab.busy = False
        with self._lock:
            for spec, slabs in self._slabs.items():
                if slab in slabs:
                    slabs.remove(slab)
                    self._cursor[spec] = 0
                    break
        self.stats["discarded"] += 1


# ------------------------------------------------------------------ the pack


class PackResult(NamedTuple):
    """A filled slab: the pack product the router stamps lane ids into."""

    slab: StagingSlab
    reasons: Optional[List[Optional[str]]]  # screening verdicts (None = guard off)
    rows: int


def pack_into_slab(
    slab: StagingSlab,
    batches: Sequence[Tuple[Any, ...]],
    rows: int,
    screen: bool,
) -> PackResult:
    """Write ``rows`` per-session rows in place into ``slab`` (the zero-copy
    pack: no per-round stack allocation) and — when ``screen`` — run the PR 8
    vectorized admission screen against the slab region directly. Any layout
    deviation (leaf count, shape, exact dtype) raises :class:`_SlabFallback`:
    the router then runs the legacy pack, whose majority-vote slow path is
    the single source of truth for mixed/malformed rounds. The slab spec IS
    the memoized uniform-round dtype reference — conformance is one dtype/shape
    identity check per row, not a per-round set rebuild."""
    from torchmetrics_tpu.quarantine import screen_slab_leaf

    spec = slab.spec
    n_leaves = len(spec.leaves)
    reasons: Optional[List[Optional[str]]] = [None] * rows if screen else None
    try:
        if any(len(b) != n_leaves for b in batches):
            raise _SlabFallback()
        for leaf_idx, (shape, _dt) in enumerate(spec.leaves):
            target = slab.args[leaf_idx]
            dtype = target.dtype
            arrs = [np.asarray(b[leaf_idx]) for b in batches]
            # exact-dtype conformance per row BEFORE the copy: np.stack's
            # out= would silently same-kind-cast (e.g. f64 rows narrowed into
            # an f32 slab), whereas the legacy pack PROMOTES the whole stack
            # — any drift must take the legacy path, not change numerics
            if not all(a.dtype == dtype for a in arrs):
                raise _SlabFallback()
            # one C-level copy straight into the slab region (raises on
            # ragged shapes -> fallback); no per-round stack allocation
            np.stack(arrs, axis=0, out=target[:rows])
    except _SlabFallback:
        raise
    except Exception as err:  # ragged / un-arrayable rows: legacy pack owns them
        rank_zero_debug(f"ingest: slab pack fell back ({type(err).__name__}: {err})")
        raise _SlabFallback() from err
    if screen:
        for leaf_idx in range(n_leaves):
            screen_slab_leaf(slab.args[leaf_idx], rows, leaf_idx, reasons)
    return PackResult(slab, reasons, rows)


class PackTicket:
    """Future for one staged pack. ``take()`` blocks for the worker's HOST
    work only (never device work), re-raises the pack's error exactly as the
    inline pack would have raised it, and returns None when the round fell
    back to the legacy path."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._value: Optional[PackResult] = None
        self._error: Optional[BaseException] = None

    def _finish(self, value: Optional[PackResult], error: Optional[BaseException]) -> None:
        self._value = value
        self._error = error
        self._event.set()

    def take(self, timeout: Optional[float] = 60.0) -> Optional[PackResult]:
        if not self._event.wait(timeout):
            return None  # a wedged worker degrades to the inline pack
        if self._error is not None:
            raise self._error
        return self._value


class IngestPipeline:
    """One daemon worker + bounded queue packing round k+1 under round k.

    ``submit`` never blocks: a full queue returns None and the router packs
    inline (the documented backpressure degradation — rounds are consumed in
    submission order either way, so no round is dropped or reordered). The
    worker reopens the submitter's trace context so the pack span carries a
    Perfetto flow arrow from the router's dispatch slice."""

    def __init__(self, maxsize: Optional[int] = None) -> None:
        if maxsize is None:
            try:
                maxsize = int(os.environ.get(QUEUE_ENV, "") or DEFAULT_QUEUE_MAXSIZE)
            except ValueError:
                maxsize = DEFAULT_QUEUE_MAXSIZE
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, maxsize))
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.stats: Dict[str, int] = {"submitted": 0, "completed": 0, "fallbacks": 0, "errors": 0, "full": 0}

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="tm_tpu_ingest_pack", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while True:
            job, ticket, ctx = self._q.get()
            try:
                self._execute(job, ticket, ctx)
            finally:
                self._q.task_done()

    def _execute(self, job: Callable[[], Optional[PackResult]], ticket: PackTicket, ctx: Any) -> None:
        with obs.use_context(ctx):
            try:
                with obs.span(obs.SPAN_PACK, histogram="lanes.pack_us", staged=True):
                    value = job()
            except _SlabFallback:
                self.stats["fallbacks"] += 1
                ticket._finish(None, None)
                return
            except BaseException as err:
                # the router re-raises this exactly where the inline pack
                # would have raised; the flight ring keeps the worker-side
                # window (pack-worker faults land in the lanes domain)
                self.stats["errors"] += 1
                rank_zero_debug(f"ingest: staged pack failed ({type(err).__name__}: {err})")
                obs.flighted(err, domain="lanes")
                ticket._finish(None, err)
                return
        self.stats["completed"] += 1
        ticket._finish(value, None)

    def submit(self, job: Callable[[], Optional[PackResult]]) -> Optional[PackTicket]:
        ticket = PackTicket()
        ctx = obs.capture_context()
        try:
            self._q.put_nowait((job, ticket, ctx))
        except queue.Full:
            self.stats["full"] += 1
            return None
        self.stats["submitted"] += 1
        self._ensure_thread()
        return ticket

    def pending(self) -> int:
        return self._q.unfinished_tasks

    def drain(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.001)
        return True


# ------------------------------------------------------- process-wide plumbing

_PIPELINE: Optional[IngestPipeline] = None
_RING: Optional[SlabRing] = None
_GLOBAL_LOCK = threading.Lock()


def get_pipeline() -> IngestPipeline:
    global _PIPELINE
    with _GLOBAL_LOCK:
        if _PIPELINE is None:
            _PIPELINE = IngestPipeline()
        return _PIPELINE


def get_ring() -> SlabRing:
    global _RING
    with _GLOBAL_LOCK:
        if _RING is None:
            _RING = SlabRing()
        return _RING


def drain_pipeline(timeout: float = 60.0) -> bool:
    """Wait for in-flight packs (tests / shutdown flushes; no-op when idle)."""
    with _GLOBAL_LOCK:
        pipeline = _PIPELINE
    return True if pipeline is None else pipeline.drain(timeout)


def reset_for_tests() -> None:
    """Drop the process-wide pipeline and ring (tests only): in-flight slabs
    stay alive through their own references; the next round rebuilds both."""
    global _PIPELINE, _RING
    with _GLOBAL_LOCK:
        _PIPELINE = None
        _RING = None


# ------------------------------------------------------- router-facing surface


def pack_async(
    pipeline: IngestPipeline,
    ring: SlabRing,
    batches: Sequence[Tuple[Any, ...]],
    rows: int,
    bucket: int,
    screen: bool,
) -> Optional[PackTicket]:
    """Stage one round's pack on the worker; None when the round cannot take
    the slab path (layout) or the queue is full (backpressure -> inline)."""
    spec = make_spec(batches, bucket)
    if spec is None:
        return None

    def job() -> Optional[PackResult]:
        slab = ring.acquire(spec, block=True)  # worker-side retire wait
        if slab is None:  # every slab checked out past the timeout: degrade
            raise _SlabFallback()
        try:
            return pack_into_slab(slab, batches, rows, screen)
        except BaseException:
            ring.release(slab)  # partially-written slab goes straight back
            raise

    # the enqueue half of the causal pair (the PR 13 compile-enqueue idiom):
    # the ambient context is captured INSIDE this span, so the worker-side
    # pack span links back to the submitting slice as a Perfetto flow arrow
    with obs.span(obs.SPAN_PACK, phase="enqueue"):
        return pipeline.submit(job)


def pack_inline(
    ring: SlabRing,
    batches: Sequence[Tuple[Any, ...]],
    rows: int,
    bucket: int,
    screen: bool,
) -> Optional[PackResult]:
    """The router-thread pack into a slab — the backpressure degradation and
    the single-round steady path. Never blocks: a busy ring (or a layout
    deviant) returns None and the caller runs the legacy pack."""
    spec = make_spec(batches, bucket)
    if spec is None:
        return None
    slab = ring.acquire(spec, block=False)
    if slab is None:
        return None
    try:
        with obs.span(obs.SPAN_PACK, histogram="lanes.pack_us", staged=False):
            return pack_into_slab(slab, batches, rows, screen)
    except _SlabFallback:
        ring.release(slab)
        return None
    except BaseException:
        ring.release(slab)
        raise


def stamp_and_upload(
    packed: PackResult, lanes: Sequence[int], sentinel: int
) -> Tuple[Any, Tuple[Any, ...]]:
    """Stamp the (possibly sentinel-diverted) lane ids into the slab's id
    vector — ALWAYS on the router thread at dispatch time, so an admission or
    eviction between pack and dispatch can never route rows into a reassigned
    lane — then upload the slab: one H2D per argument plus the id vector.
    On aliasing backends each upload copies defensively (see
    :func:`device_put_aliases_host`); the uploaded arrays are stashed on the
    slab as retire tokens for :func:`dispatch_scope`."""
    slab = packed.slab
    rows = packed.rows
    slab.lane_ids[:rows] = list(lanes)
    slab.lane_ids[rows:] = np.int32(sentinel)
    copy = device_put_aliases_host()
    ids_dev = jnp.asarray(slab.lane_ids.copy() if copy else slab.lane_ids)
    batch = tuple(jnp.asarray(a.copy() if copy else a) for a in slab.args)
    slab._upload = (ids_dev,) + batch
    obs.counter_inc("lanes.h2d_bytes", slab.nbytes())
    return ids_dev, batch


# ------------------------------------------------ executor dispatch-seam hooks

class _DispatchTLS(threading.local):
    def __init__(self) -> None:
        self.slab: Optional[StagingSlab] = None
        self.token: Optional[Any] = None


_dispatch_tls = _DispatchTLS()


class dispatch_scope:
    """Arms the executor's slab-aware dispatch seam for one round.

    The router wraps the dispatch in ``with dispatch_scope(slab):``; the
    executor calls :func:`notify_dispatched` with the state it committed, and
    on exit the slab goes in flight behind its upload tokens plus that
    committed leaf. A dispatch that raised without committing cannot prove
    the slab was fully consumed, so the slab is discarded — never reused.
    A ``None`` slab (legacy pack path) makes the whole scope a no-op."""

    __slots__ = ("_slab", "_ring")

    def __init__(self, slab: Optional[StagingSlab], ring: Optional[SlabRing] = None) -> None:
        self._slab = slab
        self._ring = ring

    def __enter__(self) -> "dispatch_scope":
        if self._slab is not None:
            _dispatch_tls.slab = self._slab
            _dispatch_tls.token = None
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        slab = self._slab
        if slab is None:
            return
        token = _dispatch_tls.token
        _dispatch_tls.slab = None
        _dispatch_tls.token = None
        ring = self._ring if self._ring is not None else get_ring()
        if token is None:
            # no committed-state token: the dispatch died, or it bypassed the
            # executor (eager fallback) and may still be reading the uploads
            # asynchronously. device_put zero-copy aliasing is decided
            # PER-ARRAY by the backend (alignment), so input tokens alone can
            # never prove the buffers are safe to overwrite — discard the
            # slab instead of ever reusing it (the degraded mode simply costs
            # what the old np.stack path always paid: a fresh allocation).
            ring.discard(slab)
            return
        ring.commit(slab, slab._upload + (token,))


def notify_dispatched(new_state: Any) -> None:
    """Executor-side half of the seam (ops/executor.py calls this right after
    committing a dispatch's new state): attach one committed leaf as the
    armed slab's strong retire token. No-op outside a :class:`dispatch_scope`
    — the seam costs one thread-local read per dispatch."""
    if _dispatch_tls.slab is None:
        return
    try:
        leaves = jax.tree_util.tree_leaves(new_state)
    except Exception as err:  # an unflattenable state yields no strong token
        rank_zero_debug(f"ingest: committed state not flattenable ({type(err).__name__}: {err})")
        leaves = []
    for leaf in leaves:
        if hasattr(leaf, "is_ready") or hasattr(leaf, "block_until_ready"):
            _dispatch_tls.token = leaf
            return
    _dispatch_tls.token = leaves[0] if leaves else None
