"""Compile-ahead layer: persistent executable cache + background compilation.

The donated-state executor (ops/executor.py) made the *warm* eager path fast,
but every fresh process still paid a cold trace + XLA compile per cache key,
and the first batch landing in a new shape bucket stalled the step loop for
the whole compile. At production scale — where restarts and preemptions
(docs/DURABILITY.md) are routine — compile latency IS the tail latency. This
module closes that gap with three cooperating pieces:

- **An on-disk executable store.** Each executor cache key maps to a stable
  content hash over ``(code hash, jax/jaxlib/library versions, backend +
  device kind, abstract input avals, donation + static-argument spec)``.
  Entries serialize the traced computation via :mod:`jax.export` (a
  StableHLO module: reloading skips the Python trace of the metric body
  entirely) and are written with the same write-to-temp → fsync → atomic
  rename discipline as state snapshots (``io.checkpoint.atomic_write_bytes``
  — the package's single durable-write primitive). Corrupt, truncated, or
  version-mismatched entries are *skipped with a warning and deleted*, never
  fatal: the worst a poisoned cache can do is cost one fresh compile.

- **JAX persistent-compilation-cache wiring.** Where ``jax.export`` cannot
  serialize a computation (exotic primitives, unexported platforms), the
  layer still wins by pointing JAX's own persistent compilation cache at
  ``<cache_dir>/xla`` (only when the user has not configured one), so the
  XLA compile — the dominant cold cost — is reused across processes even
  when the trace is not. Both tiers compose: a persisted entry's first
  dispatch compiles its StableHLO through the same persistent cache, which
  the store pre-populates at persist time.

- **A bounded background compile worker.** One daemon thread with a bounded
  queue runs (a) persist jobs — re-trace, export, serialize, atomically
  store, and pre-warm the persisted form into the XLA cache — and (b)
  stall-free miss compiles: with background mode enabled, a cold executor
  key dispatches the step through the eager op-by-op path while the compile
  runs here, and the warm executable is swapped in atomically for the next
  call (ops/executor.py). A full queue drops work (counted, retried on a
  later miss) rather than blocking the step loop.

Environment flags (see docs/EXECUTOR.md "Environment flags"):

- ``TORCHMETRICS_TPU_COMPILE_AHEAD=0`` — escape hatch: disables the whole
  layer (no disk reads/writes, no background jobs, no XLA-cache wiring).
- ``TORCHMETRICS_TPU_CACHE_DIR`` — cache location (default
  ``~/.cache/torchmetrics_tpu``).
- ``TORCHMETRICS_TPU_BG_COMPILE=1`` — enable stall-free background
  compilation of cold keys by default (off by default: it changes first-call
  semantics from "block on compile" to "serve eagerly, swap in later").
- ``TORCHMETRICS_TPU_CACHE_MAX_BYTES`` — rotating size cap for the
  executable store (default 512 MiB; oldest entries evicted first).
"""
from __future__ import annotations

import hashlib
import inspect
import json
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from torchmetrics_tpu.utils.prints import rank_zero_debug, rank_zero_warn

COMPILE_AHEAD_ENV = "TORCHMETRICS_TPU_COMPILE_AHEAD"
CACHE_DIR_ENV = "TORCHMETRICS_TPU_CACHE_DIR"
BG_COMPILE_ENV = "TORCHMETRICS_TPU_BG_COMPILE"
CACHE_MAX_BYTES_ENV = "TORCHMETRICS_TPU_CACHE_MAX_BYTES"

#: executable-entry file magic (8 bytes + newline, includes container version)
ENTRY_MAGIC = b"TMTPUXC1\n"

#: entry header schema version (bump on incompatible header changes)
ENTRY_VERSION = 1

#: executable-store entry filename suffix
ENTRY_SUFFIX = ".tmx"

#: shape-profile manifest schema version
PROFILE_VERSION = 1

DEFAULT_CACHE_MAX_BYTES = 512 * 1024 * 1024

_FALSEY = ("0", "false", "off", "no")


def compile_ahead_enabled() -> bool:
    """Master switch (``TORCHMETRICS_TPU_COMPILE_AHEAD``, on by default)."""
    return os.environ.get(COMPILE_AHEAD_ENV, "1").strip().lower() not in _FALSEY


def background_compile_default() -> bool:
    """Whether cold executor keys compile on the background worker by default
    (``TORCHMETRICS_TPU_BG_COMPILE``, off by default — it changes first-call
    semantics from "block on compile" to "serve eagerly, swap in later")."""
    return os.environ.get(BG_COMPILE_ENV, "0").strip().lower() not in _FALSEY


def cache_dir() -> Optional[str]:
    """Resolved executable-cache directory, or None when the layer is off."""
    if not compile_ahead_enabled():
        return None
    configured = os.environ.get(CACHE_DIR_ENV, "").strip()
    if configured:
        return os.path.expanduser(configured)
    return os.path.join(os.path.expanduser("~"), ".cache", "torchmetrics_tpu")


def cache_max_bytes() -> int:
    raw = os.environ.get(CACHE_MAX_BYTES_ENV, "").strip()
    try:
        return int(raw) if raw else DEFAULT_CACHE_MAX_BYTES
    except ValueError:
        rank_zero_debug(f"torchmetrics_tpu compile cache: bad {CACHE_MAX_BYTES_ENV}={raw!r}; using default")
        return DEFAULT_CACHE_MAX_BYTES


# --------------------------------------------------------------- fingerprints

_SOURCE_HASH_CACHE: Dict[Any, str] = {}
_sha = lambda data: hashlib.sha256(data).hexdigest()  # noqa: E731


def source_hash(obj: Any) -> str:
    """Cached sha256 of ``inspect.getsource(obj)`` (``"unknown"`` when the
    source is unavailable — REPL classes, frozen imports)."""
    cached = _SOURCE_HASH_CACHE.get(obj)
    if cached is None:
        try:
            cached = _sha(inspect.getsource(obj).encode())[:16]
        except (OSError, TypeError):
            cached = "unknown"
        _SOURCE_HASH_CACHE[obj] = cached
    return cached


def toolchain_fingerprint() -> str:
    """Versions + code identity shared by every entry: a jax/jaxlib/library
    bump or an edit to the executor/compile-cache machinery must invalidate
    everything (stale executables silently running old code are the one
    failure this key exists to prevent)."""
    cached = _SOURCE_HASH_CACHE.get("__toolchain__")
    if cached is None:
        import jax
        import jaxlib

        from torchmetrics_tpu import __version__
        from torchmetrics_tpu.ops import executor as executor_mod

        cached = "|".join(
            (
                f"tm_tpu={__version__}",
                f"jax={jax.__version__}",
                f"jaxlib={getattr(jaxlib, '__version__', '?')}",
                f"executor={source_hash(executor_mod)}",
                f"compile_cache={source_hash(inspect.getmodule(toolchain_fingerprint))}",
            )
        )
        _SOURCE_HASH_CACHE["__toolchain__"] = cached
    return cached


def backend_fingerprint() -> str:
    """``backend/device_kind`` of the default device — executables are
    machine-code-adjacent, so a different accelerator is a different key."""
    import jax

    try:
        dev = jax.devices()[0]
        return f"{jax.default_backend()}/{dev.device_kind}"
    except Exception as err:  # backend probing must never break dispatch
        rank_zero_debug(f"torchmetrics_tpu compile cache: backend probe failed ({err})")
        return "unknown/unknown"


def entry_key(key_desc: str) -> str:
    """Content hash naming the on-disk entry for a fully-described key."""
    return _sha(key_desc.encode())[:32]


# ------------------------------------------------------- XLA cache fallback

_XLA_CACHE_WIRED = [False]


def ensure_xla_cache_configured() -> bool:
    """Point JAX's persistent compilation cache at ``<cache_dir>/xla`` when
    the user has not configured one (idempotent, never fatal).

    This is the fallback tier: even computations ``jax.export`` cannot
    serialize get their XLA compile reused across processes. When we own the
    directory we also zero the cache thresholds — metric-update computations
    are individually small and the defaults would cache nothing.
    """
    if _XLA_CACHE_WIRED[0]:
        return True
    directory = cache_dir()
    if directory is None:
        return False
    import jax

    try:
        if getattr(jax.config, "jax_compilation_cache_dir", None):
            _XLA_CACHE_WIRED[0] = True  # user (or test harness) already owns it
            return True
        jax.config.update("jax_compilation_cache_dir", os.path.join(directory, "xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        try:
            # jax memoizes "cache disabled" on the first compile it performs;
            # a process that already compiled anything (eager ops during group
            # resolution, imports) would silently ignore the new dir without
            # this reset
            from jax._src import compilation_cache as _jax_cc

            _jax_cc.reset_cache()
        except Exception as err:
            rank_zero_debug(f"torchmetrics_tpu compile cache: jax cache reset unavailable ({err})")
        _XLA_CACHE_WIRED[0] = True
        return True
    except Exception as err:  # cache wiring is an optimization, never a crash
        rank_zero_debug(f"torchmetrics_tpu compile cache: could not wire XLA cache ({err})")
        return False


# ------------------------------------------------------------ export round-trip

#: compiled-executable pickle (jax.experimental.serialize_executable): native
#: code, near-zero reload cost, valid ONLY for the exact toolchain + backend +
#: device kind the key fingerprints pin down
FORMAT_COMPILED = "pjit_pickle"
#: portable StableHLO module (jax.export): reload skips the Python trace but
#: still pays one (persistent-cache-accelerated) XLA compile
FORMAT_STABLEHLO = "stablehlo_export"


def export_executable(jit_fn: Callable, example_args: Tuple[Any, ...]) -> List[Tuple[str, bytes]]:
    """Serialize ``jit_fn`` at the avals of ``example_args``; returns the
    entry's sections as ``[(format, blob), ...]``, best format first.

    Section 1 (when available): the AOT-compiled native executable, pickled
    (:data:`FORMAT_COMPILED`) — reload is a load, not a compile. The exact
    jax/jaxlib/backend/device-kind envelope a native executable needs is
    already part of every entry's key and header, so a mismatched binary can
    never be looked up, and a tampered one fails the header check. Section 2:
    the portable ``jax.export`` StableHLO module (:data:`FORMAT_STABLEHLO`) —
    reload re-compiles (persistent-XLA-cache-accelerated) but survives
    environments where the native form cannot be reloaded (XLA:CPU sometimes
    emits executables whose serialized form misses fusion symbols). The
    loader tries sections in order. Raises when NO section serializes —
    callers treat that as "this key stays memory-only".
    """
    import pickle

    sections: List[Tuple[str, bytes]] = []
    try:
        from jax.experimental import serialize_executable as se

        compiled = jit_fn.lower(*example_args).compile()
        payload, in_tree, out_tree = se.serialize(compiled)
        sections.append((FORMAT_COMPILED, pickle.dumps((bytes(payload), in_tree, out_tree), protocol=4)))
    except Exception as err:
        rank_zero_debug(
            f"torchmetrics_tpu compile cache: AOT executable serialization unavailable"
            f" ({type(err).__name__}: {err})"
        )
    try:
        from jax import export as jexport

        sections.append((FORMAT_STABLEHLO, bytes(jexport.export(jit_fn)(*example_args).serialize())))
    except Exception as err:
        rank_zero_debug(
            f"torchmetrics_tpu compile cache: jax.export serialization failed"
            f" ({type(err).__name__}: {err})"
        )
        if not sections:
            raise
    return sections


def deserialize_executable(blob: bytes, fmt: str = FORMAT_STABLEHLO) -> Callable:
    """Rebuild a dispatchable callable from a serialized entry.

    :data:`FORMAT_COMPILED` entries load the native executable directly
    (donation baked in at AOT-compile time; unpickling is safe here in the
    same sense jax's own persistent cache is — entries live in the user's
    cache dir, are sha256-checksummed, and are version-pinned by the key).
    :data:`FORMAT_STABLEHLO` entries wrap the exported module back under
    ``jax.jit(..., donate_argnums=0)``; their first dispatch compiles the
    StableHLO (no Python re-trace) and hits the persistent XLA cache when
    the store pre-warmed it at persist time."""
    import jax

    if fmt == FORMAT_COMPILED:
        import pickle

        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = pickle.loads(blob)
        return se.deserialize_and_load(payload, in_tree, out_tree)
    if fmt != FORMAT_STABLEHLO:
        raise ValueError(f"unknown cache entry format {fmt!r}")
    from jax import export as jexport

    exported = jexport.deserialize(bytearray(blob))
    backend = jax.default_backend()
    if exported.platforms and backend not in tuple(p.lower() for p in exported.platforms):
        raise ValueError(f"entry exported for {exported.platforms}, current backend is {backend!r}")
    return jax.jit(exported.call, donate_argnums=0)


# ----------------------------------------------------------------- disk store

def entry_path(key_hash: str, directory: Optional[str] = None) -> Optional[str]:
    directory = directory if directory is not None else cache_dir()
    if directory is None:
        return None
    return os.path.join(directory, "executables", f"{key_hash}{ENTRY_SUFFIX}")


def _entry_bytes(key_desc: str, sections: List[Tuple[str, bytes]]) -> bytes:
    payload = b"".join(blob for _, blob in sections)
    header = {
        "entry_version": ENTRY_VERSION,
        "sections": [{"format": fmt, "len": len(blob), "sha256": _sha(blob)} for fmt, blob in sections],
        "toolchain": toolchain_fingerprint(),
        "backend": backend_fingerprint(),
        "key_desc_sha256": _sha(key_desc.encode()),
        "created_unix": time.time(),
        "payload_len": len(payload),
        "payload_sha256": _sha(payload),
    }
    header_bytes = json.dumps(header, sort_keys=True).encode()
    return ENTRY_MAGIC + len(header_bytes).to_bytes(8, "little") + header_bytes + payload


def store_executable(
    key_desc: str, sections: Any, directory: Optional[str] = None
) -> Optional[str]:
    """Atomically write one entry's sections (``[(format, blob), ...]`` or a
    single ``(format, blob)`` pair); returns the path written (None when the
    store is disabled or the write failed — never raises). After a successful
    write the store is pruned to the rotating size cap."""
    if sections and isinstance(sections[0], str):
        sections = [tuple(sections)]
    if not sections:
        return None
    path = entry_path(entry_key(key_desc), directory)
    if path is None:
        return None
    from torchmetrics_tpu.io.checkpoint import atomic_write_bytes

    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_bytes(path, _entry_bytes(key_desc, list(sections)))
    except OSError as err:
        rank_zero_debug(f"torchmetrics_tpu compile cache: store failed for {path} ({err})")
        return None
    prune_store(os.path.dirname(path))
    return path


class CacheEntryInvalid(ValueError):
    """An on-disk entry failed validation (torn, corrupt, stale toolchain or
    backend). Always *handled* — the loader warns, deletes, and reports a
    miss; a poisoned cache can never crash a step or change a result."""


def _parse_entry(path: str, data: bytes, key_desc: str) -> List[Tuple[str, bytes]]:
    if len(data) < len(ENTRY_MAGIC) + 8 or not data.startswith(ENTRY_MAGIC):
        raise CacheEntryInvalid(f"{path}: bad magic / truncated header")
    hlen = int.from_bytes(data[len(ENTRY_MAGIC):len(ENTRY_MAGIC) + 8], "little")
    h_start = len(ENTRY_MAGIC) + 8
    if hlen <= 0 or h_start + hlen > len(data):
        raise CacheEntryInvalid(f"{path}: header length {hlen} exceeds file size (torn write)")
    try:
        header = json.loads(data[h_start:h_start + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as err:
        raise CacheEntryInvalid(f"{path}: header is not valid JSON ({err})") from err
    version = header.get("entry_version")
    if not isinstance(version, int) or version > ENTRY_VERSION:
        raise CacheEntryInvalid(f"{path}: entry_version {version!r} unsupported (reads <= {ENTRY_VERSION})")
    if header.get("toolchain") != toolchain_fingerprint():
        raise CacheEntryInvalid(f"{path}: stale toolchain {header.get('toolchain')!r}")
    if header.get("backend") != backend_fingerprint():
        raise CacheEntryInvalid(f"{path}: entry built for backend {header.get('backend')!r}")
    if header.get("key_desc_sha256") != _sha(key_desc.encode()):
        raise CacheEntryInvalid(f"{path}: key description mismatch (hash collision or key-logic drift)")
    payload = data[h_start + hlen:]
    if len(payload) != header.get("payload_len"):
        raise CacheEntryInvalid(
            f"{path}: payload is {len(payload)} bytes, header promises {header.get('payload_len')} (torn write)"
        )
    if _sha(payload) != header.get("payload_sha256"):
        raise CacheEntryInvalid(f"{path}: payload sha256 mismatch (corrupt write / bit rot)")
    section_meta = header.get("sections")
    if not isinstance(section_meta, list) or not section_meta:
        raise CacheEntryInvalid(f"{path}: entry has no sections")
    sections: List[Tuple[str, bytes]] = []
    offset = 0
    for meta in section_meta:
        fmt, length = meta.get("format"), meta.get("len")
        if fmt not in (FORMAT_COMPILED, FORMAT_STABLEHLO) or not isinstance(length, int):
            raise CacheEntryInvalid(f"{path}: malformed section {meta!r}")
        blob = payload[offset:offset + length]
        if len(blob) != length or _sha(blob) != meta.get("sha256"):
            raise CacheEntryInvalid(f"{path}: section {fmt!r} sha256/length mismatch")
        sections.append((fmt, blob))
        offset += length
    if offset != len(payload):
        raise CacheEntryInvalid(f"{path}: {len(payload) - offset} trailing payload bytes")
    return sections


def load_executable_blob(key_desc: str, directory: Optional[str] = None) -> Optional[List[Tuple[str, bytes]]]:
    """Validated sections ``[(format, blob), ...]`` for ``key_desc`` (best
    format first), or None on miss. A damaged or stale entry is WARNED about,
    deleted, and reported as a miss — degrading to a fresh compile is the
    contract, crashing is not."""
    path = entry_path(entry_key(key_desc), directory)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as fh:
            data = fh.read()
        return _parse_entry(path, data, key_desc)
    except CacheEntryInvalid as err:
        rank_zero_warn(
            f"torchmetrics_tpu compile cache: skipping damaged/stale entry ({err}); recompiling fresh"
        )
        try:
            os.unlink(path)
        except OSError:
            rank_zero_debug(f"torchmetrics_tpu compile cache: could not delete {path}")
        return None
    except OSError as err:
        rank_zero_debug(f"torchmetrics_tpu compile cache: read failed for {path} ({err})")
        return None


def prune_store(directory: str, max_bytes: Optional[int] = None) -> int:
    """Evict oldest entries (by mtime) until the store fits the size cap;
    returns the number of entries removed. Never fatal."""
    max_bytes = cache_max_bytes() if max_bytes is None else max_bytes
    try:
        entries = []
        with os.scandir(directory) as it:
            for de in it:
                if de.name.endswith(ENTRY_SUFFIX) and de.is_file():
                    st = de.stat()
                    entries.append((st.st_mtime, st.st_size, de.path))
    except OSError:
        return 0
    total = sum(size for _, size, _ in entries)
    removed = 0
    for _, size, path in sorted(entries):
        if total <= max_bytes:
            break
        try:
            os.unlink(path)
            total -= size
            removed += 1
        except OSError:
            rank_zero_debug(f"torchmetrics_tpu compile cache: could not evict {path}")
    return removed


# ----------------------------------------------------------- background worker

class CompileWorker:
    """One daemon thread + bounded queue running compile/persist jobs.

    Jobs are plain callables; a job that raises is recorded (``stats``,
    debug-logged) and never propagates — background compilation is an
    optimization layered on a correct eager path, so its failures only cost
    speed. ``submit`` is non-blocking: a full queue DROPS the job (counted)
    instead of stalling the step loop; the executor re-submits on a later
    miss. Thread-safe against the donation/recovery machinery by
    construction: jobs only ever touch builder closures, abstract avals, and
    fresh dummy arrays — never live metric state.
    """

    def __init__(self, maxsize: int = 16) -> None:
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)  # (job, trace ctx) pairs
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._atexit_registered = False
        self.stats = {"submitted": 0, "dropped": 0, "completed": 0, "errors": 0}

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._run, name="tm_tpu_compile_worker", daemon=True)
                self._thread.start()
                if not self._atexit_registered:
                    # the thread is daemon so a hung compile can never wedge
                    # shutdown — but interpreter teardown freezing it MID
                    # XLA-compile segfaults (observed: a cold-key dispatch as
                    # a script's last statement). Drain in-flight jobs at
                    # atexit, bounded so a wedged compile still only delays
                    # exit, never blocks it
                    import atexit

                    atexit.register(self.drain, 30.0)
                    self._atexit_registered = True

    def _run(self) -> None:
        from torchmetrics_tpu import obs  # deferred: keep import-time deps minimal

        while True:
            job, ctx = self._q.get()
            try:
                # reopen the submitting thread's trace context: the job's own
                # spans (tm_tpu.compile background=True, tm_tpu.cache.store)
                # carry the enqueue site's trace_id with a flow-event pair
                with obs.use_context(ctx):
                    job()
                self.stats["completed"] += 1
                obs.counter_inc("compile_worker.completed")
            except Exception as err:
                # background work must never crash the process; the eager
                # path it backs is already correct — record and move on
                self.stats["errors"] += 1
                obs.counter_inc("compile_worker.errors")
                obs.fault_breadcrumb(
                    "compile_worker_job_failed",
                    domain="compile",
                    data={"error": f"{type(err).__name__}: {err}"},
                )
                rank_zero_debug(
                    f"torchmetrics_tpu compile worker: job failed ({type(err).__name__}: {err})"
                )
            finally:
                self._q.task_done()
                obs.gauge_set("compile_worker.pending", self._q.unfinished_tasks)

    def submit(self, job: Callable[[], None]) -> bool:
        """Enqueue without blocking; False when the bounded queue is full.
        Captures the ambient trace context for the worker to reopen (a
        thread-local read; zero-cost when tracing is off)."""
        from torchmetrics_tpu import obs  # deferred: keep import-time deps minimal

        try:
            self._q.put_nowait((job, obs.capture_context()))
        except queue.Full:
            self.stats["dropped"] += 1
            obs.counter_inc("compile_worker.dropped")
            return False
        self.stats["submitted"] += 1
        obs.counter_inc("compile_worker.submitted")
        obs.gauge_set("compile_worker.pending", self._q.unfinished_tasks)
        self._ensure_thread()
        return True

    def pending(self) -> int:
        return self._q.unfinished_tasks

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted job finished (tests / warmup-wait);
        True when the queue drained within ``timeout``."""
        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)
        return True


_WORKER: Optional[CompileWorker] = None
_WORKER_LOCK = threading.Lock()


def get_worker() -> CompileWorker:
    """The process-wide compile worker (created on first use)."""
    global _WORKER
    with _WORKER_LOCK:
        if _WORKER is None:
            _WORKER = CompileWorker()
        return _WORKER


def drain_worker(timeout: float = 60.0) -> bool:
    """Wait for all in-flight background compiles/persists (no-op when the
    worker never started)."""
    with _WORKER_LOCK:
        worker = _WORKER
    return True if worker is None else worker.drain(timeout)


# ------------------------------------------------------ shape-profile manifests

def spec_of_call(kind: str, args: tuple, kwargs: dict) -> Optional[Dict[str, Any]]:
    """JSON-able description of one eager call's input shapes, or None when
    the call structure cannot be replayed from a manifest (nested pytrees,
    non-array leaves). Flat tuples of arrays/scalars/bools — essentially
    every metric update signature — round-trip exactly."""
    import jax

    def leaf(v: Any) -> Optional[Dict[str, Any]]:
        if type(v) is bool:
            return {"bool": v}
        if isinstance(v, (int, float)) and not isinstance(v, np.generic):
            return {"scalar": v}
        if isinstance(v, jax.core.Tracer):
            return None
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            return {"shape": [int(s) for s in v.shape], "dtype": str(v.dtype)}
        return None

    arg_specs: List[Dict[str, Any]] = []
    for a in args:
        s = leaf(a)
        if s is None:
            return None
        arg_specs.append(s)
    kw_specs: Dict[str, Any] = {}
    for k, v in kwargs.items():
        s = leaf(v)
        if s is None:
            return None
        kw_specs[k] = s
    return {"kind": kind, "args": arg_specs, "kwargs": kw_specs}


def dummy_from_spec(spec: Dict[str, Any]) -> Tuple[tuple, dict]:
    """Zero-filled concrete ``(args, kwargs)`` matching a recorded spec —
    values are irrelevant for compilation, only avals key executables."""
    import jax.numpy as jnp

    def leaf(s: Dict[str, Any]) -> Any:
        if "bool" in s:
            return bool(s["bool"])
        if "scalar" in s:
            return s["scalar"]
        return jnp.zeros(tuple(s["shape"]), dtype=s["dtype"])

    return tuple(leaf(s) for s in spec.get("args", ())), {k: leaf(s) for k, s in spec.get("kwargs", {}).items()}


def save_shape_manifest(path: str, manifest: Dict[str, Any]) -> str:
    """Atomically persist a shape-profile manifest (JSON) for
    ``warmup_from_manifest`` replay in a later process."""
    from torchmetrics_tpu.io.checkpoint import atomic_write_bytes

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    atomic_write_bytes(path, json.dumps(manifest, sort_keys=True, indent=1).encode())
    return path


def load_shape_manifest(path: str) -> Dict[str, Any]:
    """Parse and structurally validate a shape-profile manifest."""
    with open(path, "rb") as fh:
        manifest = json.loads(fh.read().decode())
    version = manifest.get("profile_version")
    if not isinstance(version, int) or version > PROFILE_VERSION:
        raise ValueError(f"{path}: profile_version {version!r} unsupported (reads <= {PROFILE_VERSION})")
    if not isinstance(manifest.get("specs"), list):
        raise ValueError(f"{path}: manifest has no 'specs' list")
    return manifest
