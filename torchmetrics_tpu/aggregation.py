"""Aggregation metrics (reference aggregation.py, 727 LoC).

``BaseAggregator`` with nan_strategy in {"error","warn","ignore", float-replacement,
"disable"}; concrete MaxMetric/MinMetric/SumMetric/CatMetric/MeanMetric and the
Running* variants (built on the Running wrapper, see wrappers/running.py).

TPU note: nan handling is expressed with ``jnp.where`` masks (trace-safe); the
"error"/"warn" strategies need concrete values and therefore only act eagerly —
under jit they degrade to "ignore"-style masking, matching XLA semantics.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _is_concrete(x: Any) -> bool:
    import jax.core

    return not isinstance(x, jax.core.Tracer)


class BaseAggregator(Metric):
    """Base class for aggregation metrics (reference aggregation.py:30).

    Args:
        fn: reduction applied on update ("sum", "max", "min", or callable)
        default_value: default state value
        nan_strategy: how to handle NaNs: "error", "warn", "ignore", "disable",
            or a float replacement value.
        state_name: name of the single state variable.
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update: Optional[bool] = False

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore", "disable")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.state_name = state_name
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)

    def _cast_and_nan_check_input(self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None):
        """Cast input to float array and handle NaNs per strategy (aggregation.py:75)."""
        x = jnp.asarray(x, dtype=jnp.float32) if not isinstance(x, jnp.ndarray) else x.astype(jnp.float32)
        if weight is not None:
            weight = (
                jnp.asarray(weight, dtype=jnp.float32) if not isinstance(weight, jnp.ndarray) else weight.astype(jnp.float32)
            )
            weight = jnp.broadcast_to(weight, x.shape)
        if self.nan_strategy == "disable":
            return x, weight
        nans = jnp.isnan(x)
        nans_w = jnp.logical_or(nans, jnp.isnan(weight)) if weight is not None else nans
        if self.nan_strategy in ("error", "warn") and _is_concrete(x):
            anynan = bool(np.any(np.asarray(nans_w)))
            if anynan:
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
        if self.nan_strategy in ("error", "warn", "ignore"):
            # mask out nan entries (trace-safe, no boolean indexing)
            if weight is not None:
                weight = jnp.where(nans_w, 0.0, weight)
            x = jnp.where(nans_w, self._nan_neutral(), x)
        else:  # float replacement
            x = jnp.where(nans_w, float(self.nan_strategy), x)
        if weight is None:
            weight = jnp.ones_like(x)
        return x, weight

    def _nan_neutral(self) -> float:
        """Value that is a no-op for this aggregator's reduction."""
        return 0.0

    def _trace_config(self) -> tuple:
        # nan_strategy changes the traced computation (neutral-mask vs float
        # replacement vs passthrough) without moving the state spec; the base
        # marker (sync_precision policy) rides along via super()
        return super()._trace_config() + (f"nan_strategy={self.nan_strategy}",)

    def _executor_traceable(self) -> bool:
        """The "error"/"warn" nan strategies need concrete values — tracing the
        update would silently skip the raise/warning, so those instances keep
        the eager path (ops/executor.py consults this hook)."""
        return self.nan_strategy not in ("error", "warn")

    def update(self, value: Union[float, Array]) -> None:
        raise NotImplementedError

    def compute(self) -> Array:
        return self._state[self.state_name]


class MaxMetric(BaseAggregator):
    """Running max aggregation (reference aggregation.py:114).

    Example:
        >>> from torchmetrics_tpu import MaxMetric
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = MaxMetric()
        >>> m.update(values)
        >>> round(float(m.compute()), 4)
        3.0
    """

    full_state_update = True
    higher_is_better = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf, dtype=jnp.float32), nan_strategy, state_name="max_value", **kwargs)

    def _nan_neutral(self) -> float:
        return -float("inf")

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.max_value = jnp.maximum(self.max_value, value.max() if value.size else jnp.asarray(-jnp.inf))


class MinMetric(BaseAggregator):
    """Running min aggregation (reference aggregation.py:219).

    Example:
        >>> from torchmetrics_tpu import MinMetric
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = MinMetric()
        >>> m.update(values)
        >>> round(float(m.compute()), 4)
        1.0
    """

    full_state_update = True
    higher_is_better = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf, dtype=jnp.float32), nan_strategy, state_name="min_value", **kwargs)

    def _nan_neutral(self) -> float:
        return float("inf")

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.min_value = jnp.minimum(self.min_value, value.min() if value.size else jnp.asarray(jnp.inf))


class SumMetric(BaseAggregator):
    """Running sum aggregation (reference aggregation.py:324).

    Example:
        >>> from torchmetrics_tpu import SumMetric
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = SumMetric()
        >>> m.update(values)
        >>> round(float(m.compute()), 4)
        6.0
    """

    full_state_update = False
    higher_is_better = None

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0, dtype=jnp.float32), nan_strategy, state_name="sum_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        self.sum_value = self.sum_value + value.sum()


class CatMetric(BaseAggregator):
    """Concatenation aggregation (reference aggregation.py:429).

    Example:
        >>> from torchmetrics_tpu import CatMetric
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = CatMetric()
        >>> m.update(values)
        >>> jnp.round(m.compute(), 4).tolist()
        [1.0, 2.0, 3.0]
    """

    full_state_update = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        return dim_zero_cat(self.value) if self.value else jnp.asarray([])


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference aggregation.py:493): states mean_value+weight.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MeanMetric
        >>> m = MeanMetric()
        >>> m.update(jnp.asarray([1.0, 3.0]))
        >>> m.update(5.0)
        >>> float(m.compute())
        3.0
    """

    full_state_update = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0, dtype=jnp.float32), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0, dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        self.mean_value = self.mean_value + (value * weight).sum()
        self.weight = self.weight + weight.sum()

    def compute(self) -> Array:
        from torchmetrics_tpu.utils.compute import _safe_divide

        return _safe_divide(self.mean_value, self.weight)


def _running_factory():
    from torchmetrics_tpu.wrappers.running import Running

    return Running


class RunningMean(Metric):
    """Mean over the last ``window`` updates (reference aggregation.py:616).

    Implemented directly (rather than through the Running wrapper) as a
    fixed-capacity ring buffer — static shapes, jit-native.

    Example:
        >>> from torchmetrics_tpu import RunningMean
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = RunningMean()
        >>> m.update(values)
        >>> round(float(m.compute()), 4)
        2.0
    """

    full_state_update = False

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.window = int(window)
        self.nan_strategy = nan_strategy
        self.add_state("values", default=jnp.zeros(self.window, dtype=jnp.float32), dist_reduce_fx=None)
        self.add_state("mask", default=jnp.zeros(self.window, dtype=jnp.bool_), dist_reduce_fx=None)
        self.add_state("cursor", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx=None)

    def _nan_filter(self, value) -> Array:
        value = jnp.asarray(value, dtype=jnp.float32)
        if self.nan_strategy in ("error", "warn", "ignore"):
            if _is_concrete(value) and bool(np.any(np.isnan(np.asarray(value)))):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                if self.nan_strategy == "warn":
                    rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
            value = jnp.where(jnp.isnan(value), 0.0, value)
        elif isinstance(self.nan_strategy, float):
            value = jnp.where(jnp.isnan(value), float(self.nan_strategy), value)
        return value

    def update(self, value: Union[float, Array]) -> None:
        value = self._nan_filter(value).mean()
        idx = self.cursor % self.window
        self.values = self.values.at[idx].set(value)
        self.mask = self.mask.at[idx].set(True)
        self.cursor = self.cursor + 1

    def compute(self) -> Array:
        from torchmetrics_tpu.utils.compute import _safe_divide

        return _safe_divide((self.values * self.mask).sum(), self.mask.sum())


class RunningSum(RunningMean):
    """Sum over the last ``window`` updates (reference aggregation.py:673).

    Example:
        >>> from torchmetrics_tpu import RunningSum
        >>> import jax.numpy as jnp
        >>> values = jnp.asarray([1.0, 2.0, 3.0])
        >>> m = RunningSum()
        >>> m.update(values)
        >>> round(float(m.compute()), 4)
        6.0
    """

    def update(self, value: Union[float, Array]) -> None:
        value = self._nan_filter(value).sum()
        idx = self.cursor % self.window
        self.values = self.values.at[idx].set(value)
        self.mask = self.mask.at[idx].set(True)
        self.cursor = self.cursor + 1

    def compute(self) -> Array:
        return (self.values * self.mask).sum()
