"""Block-quantized collectives for metric state — the wire-bandwidth engine.

At pod scale the deferred reduce (PR 3) is the ONE collective on the hot read
path, and it ships full-precision float state: a confusion matrix is C² f32,
binned PR curves are O(T·C) f32, FID's covariance sums are 768² f32. Following
EQuARX (block-quantized all-reduce inside XLA, arXiv 2506.17615), this module
moves **int8/int16 codes with per-block max-abs scales** over the mesh instead
of float32 — 4×/2× fewer payload bytes — with a documented per-element error
bound, and serves every large-state hop:

- :func:`quantized_all_reduce` — the reduce-path primitive behind the
  ``sync_precision="quantized"`` policy (``parallel/sync.py`` grouped fusion,
  ``reduce_sharded_states``, the ``ShardShadow`` refresh fold): each shard
  ships its codes + scales, receivers dequantize per source shard and apply
  the declared reduction (sum/mean/max/min).
- :func:`quantized_all_gather` — the cat/None-reduction gather (the original
  PR-era helper, upgraded from one-scale-per-tensor to per-block scales).
- :func:`encode_canonical` / :func:`decode_canonical` — the HOST-side wire
  format for ``export_canonical()`` uplinks (fleet aggregation trees ship
  folded deltas in the same codes+scales layout).

Wire format (one tensor)::

    codes  : int8|int16, shape (ceil(size/block), block)   — payload
    scales : float32,    shape (ceil(size/block),)         — one per block
    scale_b = max|x[block_b]| / (2**(bits-1) - 1)          — max-abs symmetric

Error bound (derivation in docs/SHARDING.md "Quantized reduce"): rounding to
the nearest code costs at most ``scale_b / 2`` per element, so for element
``i`` in block ``b``:

    |deq(x_s)_i - x_s_i|  <=  absmax_s(b) / (2 * qmax)            per shard s
    sum-reduce over W shards:   sum_s absmax_s(b) / (2 * qmax)
    mean-reduce:                (1/W) * sum_s absmax_s(b) / (2 * qmax)
    max/min-reduce:             max_s absmax_s(b) / (2 * qmax)

with ``qmax = 2**(bits-1) - 1`` (127 / 32767). :func:`reduce_error_bound`
computes the bound from the stacked per-shard contributions — the property
suite (tests/test_quantized_reduce.py) asserts it elementwise.

Integer-exactness guarantee: counts, bincounts and every other integer/bool
state are ALWAYS reduced exactly — :func:`block_encode` raises ``TypeError``
on non-float input, and the policy resolution in ``Metric._sync_qspecs``
never marks a non-float state quantized (enforced by a static check in
tests/test_static_checks.py).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from torchmetrics_tpu.parallel.sync import Reduction, sync_value

_INT_DTYPES = {8: jnp.int8, 16: jnp.int16}

#: env var holding the fleet-wide default sync precision ("exact" | "quantized")
SYNC_PRECISION_ENV = "TORCHMETRICS_TPU_SYNC_PRECISION"

SYNC_PRECISIONS = ("exact", "quantized")

#: default code width (bits) and block size (elements per scale) of the
#: quantized wire format; per-metric overrides via ``sync_quant_bits`` /
#: ``sync_quant_block``
DEFAULT_BITS = 8
DEFAULT_BLOCK = 256

#: a resolved per-state quantization spec: None = exact, else (bits, block)
QSpec = Optional[Tuple[int, int]]


def default_sync_precision() -> str:
    """The environment-configured sync precision (``TORCHMETRICS_TPU_SYNC_PRECISION``).

    ``"exact"`` (default) keeps full-precision collectives; ``"quantized"``
    opts every *float* state into the block-quantized reduce path (integer
    states always stay exact regardless).
    """
    raw = os.environ.get(SYNC_PRECISION_ENV, "").strip().lower()
    if not raw:
        return "exact"
    if raw not in SYNC_PRECISIONS:
        raise ValueError(f"{SYNC_PRECISION_ENV} must be one of {SYNC_PRECISIONS}, got {raw!r}")
    return raw


def _qmax(bits: int) -> float:
    if bits not in _INT_DTYPES:
        raise ValueError(f"bits must be one of {sorted(_INT_DTYPES)}, got {bits}")
    return float(2 ** (bits - 1) - 1)


def block_encode(x: Array, bits: int = DEFAULT_BITS, block_size: int = DEFAULT_BLOCK):
    """Max-abs symmetric per-block quantization: ``(codes, scales)``.

    ``codes`` is ``(n_blocks, block_size)`` int8/int16 (zero-padded tail),
    ``scales`` is ``(n_blocks,)`` f32. Raises ``TypeError`` on integer/bool
    input — the integer-exactness guarantee is enforced at the encoder, so no
    caller bug can ever round a count.
    """
    qmax = _qmax(bits)
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(
            f"block_encode: refusing to quantize non-float dtype {x.dtype} — integer-exact"
            " states (counts, bincounts) must take the exact reduce path"
        )
    flat = x.ravel().astype(jnp.float32)
    pad = (-flat.size) % block_size
    blocks = jnp.pad(flat, (0, pad)).reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(blocks / scales[:, None]), -qmax, qmax).astype(_INT_DTYPES[bits])
    return codes, scales


def block_decode(codes: Array, scales: Array, size: int, shape: tuple, dtype: Any) -> Array:
    """Inverse of :func:`block_encode`: dequantize and restore shape/dtype."""
    deq = codes.astype(jnp.float32) * jnp.asarray(scales)[..., None].astype(jnp.float32)
    return deq.reshape(deq.shape[:-2] + (-1,))[..., :size].reshape(shape).astype(dtype)


def quantized_all_reduce(
    x: Array,
    axis_name: Union[str, Sequence[str]],
    reduction: str = "sum",
    bits: int = DEFAULT_BITS,
    block_size: int = DEFAULT_BLOCK,
) -> Array:
    """All-reduce ``x`` over ``axis_name`` with int codes + per-block scales
    on the wire — the EQuARX-direction replacement for ``lax.psum`` (and
    pmean/pmax/pmin) on large float states.

    Each shard encodes against its own per-block max-abs scales; the codes and
    scales are gathered and the receiver dequantizes per source shard before
    applying ``reduction``. Output matches the exact collective up to the
    module-docstring error bound, and is IDENTICAL on every shard (the same
    dequantize-and-accumulate arithmetic runs replicated).
    """
    if reduction not in ("sum", "mean", "max", "min"):
        raise ValueError(f"quantized_all_reduce supports sum/mean/max/min, got {reduction!r}")
    x = jnp.asarray(x)
    codes, scales = block_encode(x, bits=bits, block_size=block_size)
    g_codes = lax.all_gather(codes, axis_name, axis=0)  # (W, n_blocks, block)
    g_scales = lax.all_gather(scales, axis_name, axis=0)  # (W, n_blocks)
    deq = g_codes.astype(jnp.float32) * g_scales[..., None]
    if reduction == "sum":
        acc = deq.sum(0)
    elif reduction == "mean":
        acc = deq.mean(0)
    elif reduction == "max":
        acc = deq.max(0)
    else:
        acc = deq.min(0)
    return acc.ravel()[: x.size].reshape(x.shape).astype(x.dtype)


def quantized_all_gather(
    x: Array,
    axis_name: Union[str, Sequence[str]],
    bits: int = DEFAULT_BITS,
    block_size: int = DEFAULT_BLOCK,
) -> Array:
    """All-gather ``x`` over ``axis_name`` with an int payload on the wire.

    Each shard sends per-block codes + f32 scales; the receiver dequantizes
    per source shard. Output matches ``lax.all_gather(x, axis_name, axis=0)``
    up to one half-step of each element's block scale (the per-shard row of
    the module-docstring bound).
    """
    x = jnp.atleast_1d(x)
    codes, scales = block_encode(x, bits=bits, block_size=block_size)
    g_codes = lax.all_gather(codes, axis_name, axis=0)  # (W, n_blocks, block)
    g_scales = lax.all_gather(scales, axis_name, axis=0)  # (W, n_blocks)
    world = g_codes.shape[0]
    return block_decode(g_codes, g_scales, x.size, (world,) + x.shape, x.dtype)


def reduce_error_bound(
    stacked: Any, reduction: str, bits: int = DEFAULT_BITS, block_size: int = DEFAULT_BLOCK
) -> np.ndarray:
    """Elementwise upper bound on ``|quantized_reduce - exact_reduce|`` given
    the stacked per-shard contributions ``stacked`` with shape ``(W, *shape)``
    (host-side; the property-test oracle for the documented bound)."""
    arr = np.asarray(stacked, dtype=np.float64)
    world = arr.shape[0]
    flat = arr.reshape(world, -1)
    size = flat.shape[1]
    pad = (-size) % block_size
    blocks = np.pad(flat, ((0, 0), (0, pad))).reshape(world, -1, block_size)
    absmax = np.abs(blocks).max(axis=2)  # (W, n_blocks)
    per_shard = absmax / (2.0 * _qmax(bits))  # half a quantization step
    if reduction == "sum":
        per_block = per_shard.sum(axis=0)
    elif reduction == "mean":
        per_block = per_shard.mean(axis=0)
    else:  # max/min: the winning shard is off by at most its own half step
        per_block = per_shard.max(axis=0)
    per_elem = np.repeat(per_block, block_size)[:size]
    return per_elem.reshape(arr.shape[1:])


# ---------------------------------------------------------------------------
# Wire-byte accounting (the sync.bytes_on_wire counter + bench config 2)
# ---------------------------------------------------------------------------

#: bytes of one f32 scale on the wire
_SCALE_BYTES = 4


def quantized_wire_bytes(num_elements: int, bits: int, block_size: int) -> Dict[str, int]:
    """Payload bytes one shard injects for ``num_elements`` quantized values:
    ``{"codes", "scales", "total"}``. Codes are the float-state payload the
    4×/2× claim is about; scales are the per-block side channel
    (``4 / block_size`` bytes per element — 1.6 % at the default block 256)."""
    n_blocks = -(-int(num_elements) // int(block_size))
    codes = n_blocks * block_size * (bits // 8)
    scales = n_blocks * _SCALE_BYTES
    return {"codes": codes, "scales": scales, "total": codes + scales}


def state_wire_bytes(
    states: Dict[str, Any],
    reductions: Dict[str, Reduction],
    qspecs: Optional[Dict[str, QSpec]] = None,
) -> Dict[str, int]:
    """Analytic bytes one shard injects to sync ``states`` once:
    ``{"exact", "codes", "scales", "total"}`` — exact fields contribute their
    raw nbytes, quantized fields their codes + scales. Host metadata only
    (shapes/dtypes), zero device work; bench config 2 records the
    quantized-vs-exact deltas from this."""
    out = {"exact": 0, "codes": 0, "scales": 0}
    for name, value in states.items():
        vals = value if isinstance(value, (list, tuple)) else [value]
        for v in vals:
            arr = np.asarray(jnp.asarray(v)) if not hasattr(v, "dtype") else v
            size = int(np.prod(np.shape(arr))) if np.shape(arr) else 1
            nbytes = size * np.dtype(arr.dtype).itemsize
            q = (qspecs or {}).get(name)
            if q is not None and jnp.issubdtype(jnp.asarray(arr).dtype, jnp.floating):
                bits, block = q
                qb = quantized_wire_bytes(size, bits, block)
                out["codes"] += qb["codes"]
                out["scales"] += qb["scales"]
            else:
                out["exact"] += nbytes
    out["total"] = out["exact"] + out["codes"] + out["scales"]
    return out


# ---------------------------------------------------------------------------
# Host-side wire format: export_canonical() uplinks (fleet aggregation trees)
# ---------------------------------------------------------------------------

#: wire-format version stamp carried by every encoded payload
WIRE_VERSION = 1


def encode_canonical(
    states: Dict[str, Any],
    qspecs: Optional[Dict[str, QSpec]] = None,
    bits: int = DEFAULT_BITS,
    block_size: int = DEFAULT_BLOCK,
) -> Dict[str, Any]:
    """Encode a canonical (folded, host-side) state pytree into the wire
    format an uplink ships: float fields marked quantized (by ``qspecs``, or
    ALL float fields when ``qspecs`` is None) become codes + per-block scales;
    integer/bool fields always ride raw. Inverse: :func:`decode_canonical`."""
    fields: Dict[str, Any] = {}
    for name, value in states.items():
        arr = np.asarray(value)
        q = qspecs.get(name, None) if qspecs is not None else (bits, block_size)
        if q is not None and np.issubdtype(arr.dtype, np.floating):
            b, blk = q
            codes, scales = block_encode(jnp.asarray(arr), bits=b, block_size=blk)
            fields[name] = {
                "enc": "q",
                "bits": int(b),
                "block": int(blk),
                "codes": np.asarray(codes),
                "scales": np.asarray(scales),
                "shape": tuple(int(d) for d in arr.shape),
                "dtype": str(arr.dtype),
            }
        else:
            fields[name] = {"enc": "raw", "data": arr}
    return {"wire_version": WIRE_VERSION, "fields": fields}


def decode_canonical(wire: Dict[str, Any]) -> Dict[str, Any]:
    """Decode an :func:`encode_canonical` payload back to a host pytree."""
    if wire.get("wire_version") != WIRE_VERSION:
        raise ValueError(f"unknown wire_version {wire.get('wire_version')!r} (expected {WIRE_VERSION})")
    out: Dict[str, Any] = {}
    for name, f in wire["fields"].items():
        if f["enc"] == "raw":
            out[name] = np.asarray(f["data"])
        else:
            size = int(np.prod(f["shape"])) if f["shape"] else 1
            deq = np.asarray(f["codes"], dtype=np.float32) * np.asarray(f["scales"])[..., None]
            out[name] = deq.reshape(-1)[:size].reshape(f["shape"]).astype(f["dtype"])
    return out


def wire_payload_bytes(wire: Dict[str, Any]) -> int:
    """Total bytes of one encoded uplink payload (codes + scales + raw)."""
    total = 0
    for f in wire["fields"].values():
        if f["enc"] == "raw":
            total += int(np.asarray(f["data"]).nbytes)
        else:
            total += int(np.asarray(f["codes"]).nbytes) + int(np.asarray(f["scales"]).nbytes)
    return total


# ---------------------------------------------------------------------------
# The opt-in dist_sync_fn (the original helper, now per-block underneath)
# ---------------------------------------------------------------------------

def quantized_sync(bits: int = DEFAULT_BITS) -> Callable[[Any, Reduction, Union[str, Sequence[str]]], Any]:
    """A drop-in ``dist_sync_fn``: quantized gather for float cat/None states.

    Everything else (exact psum-family reductions, integer/bool payloads,
    custom callables) defers to the exact :func:`sync_value` path. For the
    reduce-path policy (psum-family states too), use
    ``sync_precision="quantized"`` on the metric instead.

    Example:
        >>> from torchmetrics_tpu.parallel import quantized_sync
        >>> from torchmetrics_tpu.aggregation import CatMetric
        >>> metric = CatMetric(dist_sync_fn=quantized_sync(bits=8))  # opt in per metric
        >>> metric.dist_sync_fn.__name__
        'quantized_sync_8'
    """

    def _sync(value: Any, reduction: Reduction, axis_name: Union[str, Sequence[str]]) -> Any:
        is_list = isinstance(value, (list, tuple))
        if reduction in ("cat", None) and not callable(reduction):
            payload = value
            if is_list:
                if len(payload) == 0:
                    return payload
                payload = jnp.concatenate([jnp.atleast_1d(v) for v in payload], axis=0)
            if jnp.issubdtype(payload.dtype, jnp.floating):
                gathered = quantized_all_gather(payload, axis_name, bits=bits)
                out = gathered.reshape((-1,) + gathered.shape[2:]) if reduction == "cat" else gathered
                return [out] if is_list else out
        return sync_value(value, reduction, axis_name)

    _sync.__name__ = f"quantized_sync_{bits}"
    return _sync


quantized_sync_int8 = partial(quantized_sync, 8)
quantized_sync_int16 = partial(quantized_sync, 16)
