"""Quantized gather for large metric states — an ICI-bandwidth optimization.

Concatenation-reduced ("cat"/None) states are the one sync path whose cost grows
with O(world · |state|): feature buffers (KID/IS), capacity-buffered curves and
retrieval grids can reach megabytes per chip. Following the EQuARX direction
(quantized collectives in XLA, arxiv 2506.17615), `quantized_all_gather` moves
int8/int16 payloads over the mesh instead of float32 — 4x/2x fewer bytes on the
wire — with one max-abs scale per source shard gathered alongside.

Sum/mean/max/min reductions stay exact `psum`-family ops (already O(|state|);
quantizing them would change results for no bandwidth win at metric-state
sizes). Opt in per metric:

    metric = KernelInceptionDistance(..., dist_sync_fn=quantized_sync(bits=8))

The error of a gathered value is bounded by ``max|x| / (2**(bits-1) - 1)`` per
source shard (half a quantization step after rounding).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Union

import jax.numpy as jnp
from jax import Array, lax

from torchmetrics_tpu.parallel.sync import Reduction, sync_value

_INT_DTYPES = {8: jnp.int8, 16: jnp.int16}


def _encode(x: Array, bits: int):
    """Max-abs symmetric quantization: (codes, scale)."""
    qmax = float(2 ** (bits - 1) - 1)
    absmax = jnp.max(jnp.abs(x))
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    codes = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(_INT_DTYPES[bits])
    return codes, scale


def quantized_all_gather(x: Array, axis_name: Union[str, Sequence[str]], bits: int = 8) -> Array:
    """All-gather ``x`` over ``axis_name`` with an int payload on the wire.

    Each shard sends its values quantized against its own max-abs scale plus one
    f32 scalar; the receiver dequantizes per source shard. Output matches
    ``lax.all_gather(x, axis_name, axis=0)`` up to quantization error.
    """
    if bits not in _INT_DTYPES:
        raise ValueError(f"bits must be one of {sorted(_INT_DTYPES)}, got {bits}")
    x = jnp.atleast_1d(x)
    codes, scale = _encode(x, bits)
    gathered_codes = lax.all_gather(codes, axis_name, axis=0)      # (W, *x.shape)
    gathered_scales = lax.all_gather(scale, axis_name, axis=0)     # (W,)
    expand = (-1,) + (1,) * x.ndim
    return gathered_codes.astype(x.dtype) * gathered_scales.reshape(expand).astype(x.dtype)


def quantized_sync(bits: int = 8) -> Callable[[Any, Reduction, Union[str, Sequence[str]]], Any]:
    """A drop-in ``dist_sync_fn``: quantized gather for float cat/None states.

    Everything else (exact psum-family reductions, integer/bool payloads,
    custom callables) defers to the exact :func:`sync_value` path.

    Example:
        >>> from torchmetrics_tpu.parallel import quantized_sync
        >>> from torchmetrics_tpu.aggregation import CatMetric
        >>> metric = CatMetric(dist_sync_fn=quantized_sync(bits=8))  # opt in per metric
        >>> metric.dist_sync_fn.__name__
        'quantized_sync_8'
    """

    def _sync(value: Any, reduction: Reduction, axis_name: Union[str, Sequence[str]]) -> Any:
        is_list = isinstance(value, (list, tuple))
        if reduction in ("cat", None) and not callable(reduction):
            payload = value
            if is_list:
                if len(payload) == 0:
                    return payload
                payload = jnp.concatenate([jnp.atleast_1d(v) for v in payload], axis=0)
            if jnp.issubdtype(payload.dtype, jnp.floating):
                gathered = quantized_all_gather(payload, axis_name, bits=bits)
                out = gathered.reshape((-1,) + gathered.shape[2:]) if reduction == "cat" else gathered
                return [out] if is_list else out
        return sync_value(value, reduction, axis_name)

    _sync.__name__ = f"quantized_sync_{bits}"
    return _sync


quantized_sync_int8 = partial(quantized_sync, 8)
quantized_sync_int16 = partial(quantized_sync, 16)
