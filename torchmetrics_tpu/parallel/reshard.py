"""Elastic topology: the ONE audited N→M reshard seam + the shard shadow.

Every robustness layer before this PR assumed the *world is fixed*: a
checkpoint saved on an 8-device mesh restores only onto 8 devices, and a
deferred-mode shard that dies takes its locally-accumulated counts with it.
Large TPU jobs are routinely preempted and rescheduled onto a *different*
slice shape (arXiv:2204.06514), so metric state must survive a changed
world. This module is the seam everything elastic routes through
(docs/SHARDING.md "Resharding", docs/DURABILITY.md "Elastic restore"):

- :func:`fold_canonical` — collapse a stacked sharded state (leading axis =
  num_shards) to the **topology-neutral canonical form**: the exact value
  the declared ``dist_reduce_fx`` would produce at the read point. Canonical
  state has no shard axis and can be reinstalled on ANY world.
- :func:`expand_canonical` — reinstall a canonical value onto M shards
  exactly: the folded value becomes the carried content and fresh identity
  accumulators fill the rest, per reduction family (see below).
- :func:`merge_folded` — combine two canonical *segments* (a carried
  baseline and a freshly-folded live value) per the declared reduction.
- :func:`reshard_states` — the audited N→M path built from the two halves;
  ``DeferredCollectionStep.restore_states``, the elastic checkpoint restore
  (io/checkpoint.py) and the shard-loss recovery all call THIS function, so
  re-splitting logic exists exactly once.
- :class:`ShardShadow` — a bounded-lag host-side shadow of the folded
  reduce for deferred state, refreshed through the async read pipeline
  (ops/async_read.py): the step loop only *dispatches* the (non-donating)
  fold executable; the ready-wait and D2H land on the pipeline worker. On
  shard loss the shadow is what ``on_shard_loss="degraded"|"restore"``
  serves or reinstalls.

Exactness per reduction family (why elastic restore is exact, not
approximate):

====== ============================== ===============================
family fold (shard axis)              expand onto M shards
====== ============================== ===============================
sum    add                            canonical in shard 0, zeros elsewhere
mean   linear (mean over shards)      canonical REPLICATED on every shard —
                                      ``mean_i(b + c_i) = b + mean_i(c_i)``
max    idempotent                     canonical replicated
min    idempotent                     canonical replicated
cat    concat                         cannot live in a uniform stack: the
                                      canonical value is carried as a host
                                      baseline and merged at the read point
====== ============================== ===============================

``None``/callable reductions have no derivable identity or segment merge;
elastic restore refuses them (``TopologyMismatchError``) — save/restore on
matching topology (``topology="strict"``) instead.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.parallel.sync import (
    Reduction,
    reduce_stacked,
    reduction_identity,
)
from torchmetrics_tpu.utils.exceptions import TopologyMismatchError

__all__ = [
    "ShardLayout",
    "ShardShadow",
    "expand_canonical",
    "fold_canonical",
    "layout_of",
    "merge_folded",
    "reshard_states",
]

#: reduction families an elastic reshard can re-split exactly INTO the stack
_IN_STACK = ("sum", "mean", "max", "min")

#: reserved keys a state export may carry that are not declared fields
_COUNT_KEY = "_update_count"
_SHARDS_KEY = "_sharded_shards"
#: windowed exports carry their ring geometry under this key (windows.py) —
#: host metadata, never reduced; the window CLOCK itself rides the declared
#: ``window_head`` state field (fx="max": exact through fold AND expand)
_WINDOW_META_KEY = "_window_meta"


class ShardLayout(NamedTuple):
    """Topology descriptor of a stacked sharded state: how many per-device
    shards the leading axis carries (the deferred layout of docs/SHARDING.md).
    ``axis_name`` records the mesh axis the layout partitions along (metadata
    only — the fold/expand arithmetic never needs it)."""

    num_shards: int
    axis_name: Optional[str] = None


def layout_of(states: Dict[str, Any]) -> ShardLayout:
    """Infer the :class:`ShardLayout` of a stacked state pytree from its
    first array leaf's leading axis (every leaf agrees by construction —
    ``Metric.validate_state(sharded=True)`` enforces it on restore paths)."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    for v in states.values():
        if isinstance(v, dict):
            return layout_of(v)
        arr = v if hasattr(v, "shape") else np.asarray(v)
        if getattr(arr, "ndim", 0) >= 1:
            return ShardLayout(int(arr.shape[0]))
    raise obs.flighted(
        TopologyMismatchError("cannot infer shard layout: no array leaf carries a shard axis"),
        domain="reshard",
    )


def _strip_reserved(states: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in states.items() if k not in (_COUNT_KEY, _SHARDS_KEY, _WINDOW_META_KEY)}


def fold_canonical(
    states: Dict[str, Any],
    reductions: Dict[str, Reduction],
    class_layouts: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Collapse the leading shard axis of every field per its declared
    reduction — the topology-neutral canonical form (the same arithmetic as
    ``parallel.sync.fold_sharded_states``; reserved count/shard-mark keys are
    stripped). Works on host (np) and device (jnp) stacks alike.

    ``class_layouts`` (field name → ``ClassShardLayout``) additionally
    concatenates class-axis stacked fields back to their DENSE class axis, so
    the canonical form stays neutral to BOTH topologies — the data-axis shard
    count and the class-axis shard count (docs/SHARDING.md "Class-axis state
    sharding"). The class gather is a pure metadata reshape + trim, exact for
    every eligible reduction."""
    from torchmetrics_tpu.parallel.class_shard import gather_dense

    folded = {
        k: reduce_stacked(v if hasattr(v, "sum") else np.asarray(v), reductions.get(k))
        for k, v in _strip_reserved(states).items()
    }
    for name, layout in (class_layouts or {}).items():
        if name in folded:
            folded[name] = gather_dense(folded[name], layout)
    return folded


def expand_canonical(
    canonical: Dict[str, Any],
    reductions: Dict[str, Reduction],
    num_shards: int,
    class_layouts: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Reinstall a canonical (folded) state onto ``num_shards`` shards such
    that the next fold returns exactly the canonical value and subsequent
    local accumulation stays exact (the table in the module docstring).

    ``class_layouts`` re-splits dense class axes into the target's class
    stack (identity-padded) BEFORE the data-axis expand — the inverse of
    :func:`fold_canonical`'s class gather, so an N-device/S-shard save
    reinstalls exactly onto an M-device/S'-shard world.

    Raises :class:`TopologyMismatchError` for fields whose reduction cannot
    be re-split into a uniform stack (``cat``, ``None``, callables) — those
    are carried as a read-point baseline instead (:func:`merge_folded`)."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies
    from torchmetrics_tpu.parallel.class_shard import identity_pad_value, stack_dense

    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    out: Dict[str, Any] = {}
    for name, value in _strip_reserved(canonical).items():
        fx = reductions.get(name)
        layout = (class_layouts or {}).get(name)
        if layout is not None:
            value = stack_dense(
                value, layout, pad_value=identity_pad_value(fx, jnp.asarray(value).dtype)
            )
        if fx not in _IN_STACK:
            raise obs.flighted(
                TopologyMismatchError(
                    f"field {name!r} (dist_reduce_fx={fx!r}) cannot be re-split into a"
                    f" {num_shards}-shard stack — carry it as a baseline (merge_folded)"
                    " or restore on the saved topology"
                ),
                domain="reshard",
            )
        arr = jnp.asarray(value)
        if fx == "sum":
            ident = jnp.broadcast_to(
                reduction_identity(fx, arr.dtype), (num_shards - 1,) + arr.shape
            )
            out[name] = jnp.concatenate([arr[None], ident], axis=0)
        else:  # mean (linear fold), max/min (idempotent): replicate exactly
            out[name] = jnp.broadcast_to(arr[None], (num_shards,) + arr.shape)
    return out


def merge_folded(
    baseline: Dict[str, Any], fresh: Dict[str, Any], reductions: Dict[str, Reduction]
) -> Dict[str, Any]:
    """Combine two canonical *segments* of the same accumulation — a carried
    baseline (everything folded before the topology change / shard loss) and
    a freshly-folded live value — per the declared reduction.

    Segment combination differs from the shard fold itself for ``mean``: the
    fold over the shard axis is LINEAR, so two folded segments of the same
    physical accumulators combine by addition (``mean_i(a_i + c_i) =
    mean_i(a_i) + mean_i(c_i)``) — exactly what an uninterrupted run's single
    fold would have produced."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    out: Dict[str, Any] = {}
    for name, b in baseline.items():
        fx = reductions.get(name)
        v = fresh[name]
        if fx in ("sum", "mean"):
            out[name] = b + v
        elif fx == "max":
            out[name] = jnp.maximum(b, v)
        elif fx == "min":
            out[name] = jnp.minimum(b, v)
        elif fx == "cat":
            out[name] = jnp.concatenate([jnp.atleast_1d(jnp.asarray(b)), jnp.atleast_1d(jnp.asarray(v))], axis=0)
        else:
            raise obs.flighted(
                TopologyMismatchError(
                    f"field {name!r} (dist_reduce_fx={fx!r}) has no derivable segment merge;"
                    " elastic restore cannot carry it across a topology change"
                ),
                domain="reshard",
            )
    for name, v in fresh.items():
        if name not in out:
            out[name] = v
    return out


def reshard_states(
    states: Dict[str, Any],
    from_layout: ShardLayout,
    to_layout: ShardLayout,
    reductions: Dict[str, Reduction],
    class_layouts: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The audited N→M re-split: fold ``states`` (stacked with
    ``from_layout.num_shards`` leading) to canonical, then expand onto
    ``to_layout.num_shards`` shards. Exact for the sum/mean/max/min families
    (module docstring table); ``cat``/``None``/callable fields raise
    :class:`TopologyMismatchError` — carry those as a read-point baseline.

    N == M is a validated no-op (the stack is returned unchanged), so every
    restore path can route through here unconditionally and the mismatch
    logic lives in exactly one place.
    """
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    got = layout_of(states)
    if got.num_shards != from_layout.num_shards:
        raise obs.flighted(
            TopologyMismatchError(
                f"state carries {got.num_shards} shards but from_layout declares"
                f" {from_layout.num_shards}",
                saved={"num_shards": from_layout.num_shards},
                current={"num_shards": got.num_shards},
            ),
            domain="reshard",
        )
    if from_layout.num_shards == to_layout.num_shards:
        return _strip_reserved(states)
    with obs.span(obs.SPAN_RESHARD, src=from_layout.num_shards, dst=to_layout.num_shards):
        obs.counter_inc("shards.resharded")
        return expand_canonical(
            fold_canonical(states, reductions, class_layouts),
            reductions,
            to_layout.num_shards,
            class_layouts,
        )


# ---------------------------------------------------------------------------
# Shard-loss tolerance: the bounded-lag host shadow of the folded reduce
# ---------------------------------------------------------------------------

#: valid ``on_shard_loss`` policies (docs/ROBUSTNESS.md "Shard loss")
SHARD_LOSS_POLICIES = ("raise", "degraded", "restore")


class ShardShadow:
    """Bounded-lag host copy of a deferred accumulation's folded reduce.

    The deferred layout's whole point is that unreduced state lives only on
    the devices — which means a lost shard loses history. The shadow closes
    that hole without new blocking points: every ``every_n_steps`` local
    steps the owner *dispatches* its (separately compiled, non-donating)
    fold executable — JAX async dispatch, zero wait on the step loop — and
    hands the resulting replicated arrays to the async read pipeline, whose
    worker does the ready-wait + D2H (the ONLY sanctioned blocking points,
    tools/lint_blocking_host_sync.py). The freshest completed refresh is the
    recovery anchor: at most ``every_n_steps - 1`` updates behind the live
    state, plus whatever is still in flight.

    The shadow value is CANONICAL (topology-neutral, :func:`fold_canonical`
    shape), so recovery composes with elastic restore: a shard lost at the
    same moment the world is resized reinstalls through the same
    :func:`reshard_states`/baseline seam.
    """

    def __init__(
        self,
        reductions_of: Callable[[], Dict[str, Dict[str, Reduction]]],
        every_n_steps: int = 8,
    ) -> None:
        if every_n_steps < 1:
            raise ValueError(f"every_n_steps must be >= 1, got {every_n_steps}")
        self.every_n_steps = int(every_n_steps)
        self._reductions_of = reductions_of
        self._lock = threading.Lock()
        #: freshest COMPLETED refresh: (canonical host pytree, step counter)
        self._shadow: Optional[Tuple[Dict[str, Dict[str, Any]], int]] = None
        self._last_submitted = -every_n_steps  # first observe() always refreshes
        self.stats: Dict[str, int] = {"refreshes": 0, "submitted": 0, "errors": 0}

    # ------------------------------------------------------------- observation
    def due(self, step_count: int) -> bool:
        """True when the cadence says a refresh should be submitted now."""
        return step_count - self._last_submitted >= self.every_n_steps

    def observe(self, folded_device: Any, step_count: int, baseline: Optional[Dict[str, Any]] = None) -> None:
        """Stage one refresh: ``folded_device`` is the ALREADY-DISPATCHED
        output of the owner's fold executable (fresh non-donated buffers —
        later donating local steps cannot invalidate them). The worker-side
        job materializes it, host-copies, merges any carried ``baseline``
        segment, and installs the result as the freshest shadow."""
        from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies
        from torchmetrics_tpu.ops.async_read import get_pipeline

        self._last_submitted = int(step_count)
        self.stats["submitted"] += 1
        # the submit span is the flow source: the pipeline captures the
        # ambient context inside it, so the worker-side refresh links back
        # here with a Perfetto flow arrow (step loop -> pipeline worker)
        with obs.span(obs.SPAN_SHADOW, phase="submit", step=int(step_count)):
            get_pipeline().submit(
                lambda: self._refresh_job(folded_device, int(step_count), baseline),
                owner="ShardShadow.refresh",
            )

    def _refresh_job(self, folded_device: Any, step_count: int, baseline: Optional[Dict[str, Any]]) -> None:
        """WORKER-SIDE ONLY (async read pipeline): ready-wait + D2H + install."""
        from torchmetrics_tpu import obs
        from torchmetrics_tpu.ops.async_read import materialize

        try:
            with obs.span(obs.SPAN_SHADOW, phase="refresh", step=int(step_count)):
                ready = materialize(folded_device)
                host = {
                    leader: {f: np.array(v) for f, v in sub.items()}
                    for leader, sub in ready.items()
                }
            if baseline is not None:
                reds = self._reductions_of()
                host = {
                    leader: {
                        f: np.asarray(v)
                        for f, v in merge_folded(baseline[leader], sub, reds[leader]).items()
                    }
                    for leader, sub in host.items()
                }
            with self._lock:
                # refreshes resolve in submission order (single worker), but a
                # stale install would still be wrong after a recover() reset
                if self._shadow is None or step_count >= self._shadow[1]:
                    self._shadow = (host, step_count)
            self.stats["refreshes"] += 1
            obs.counter_inc("shards.shadow_refreshes")
        except Exception as err:
            # a failed refresh must not kill the pipeline; the previous shadow
            # stays the recovery anchor (lag grows, visible in the gauge)
            from torchmetrics_tpu.utils.prints import rank_zero_debug

            self.stats["errors"] += 1
            obs.counter_inc("shards.shadow_errors")
            obs.fault_breadcrumb(
                "shadow_refresh_failed",
                domain="shadow",
                data={"error": f"{type(err).__name__}: {err}"},
            )
            rank_zero_debug(f"shard shadow refresh failed: {type(err).__name__}: {err}")

    # ------------------------------------------------------------------ reads
    def snapshot(self) -> Optional[Tuple[Dict[str, Dict[str, Any]], int]]:
        """The freshest completed refresh as ``(canonical_host_state,
        step_counter)``, or None when no refresh has completed yet."""
        with self._lock:
            if self._shadow is None:
                return None
            host, count = self._shadow
            return {k: dict(v) for k, v in host.items()}, count

    def seed(self, canonical: Dict[str, Dict[str, Any]], step_count: int) -> None:
        """Install a known-good canonical value directly (restore-time seed /
        post-recovery reset) without a device round-trip."""
        host = {
            leader: {f: np.asarray(v) for f, v in sub.items()} for leader, sub in canonical.items()
        }
        with self._lock:
            self._shadow = (host, int(step_count))
        self._last_submitted = int(step_count)

    def updates_behind(self, live_step_count: int) -> Optional[int]:
        """How many committed local steps the shadow trails the live state by
        (the staleness contract of docs/ROBUSTNESS.md); None before the first
        completed refresh."""
        with self._lock:
            if self._shadow is None:
                return None
            return max(0, int(live_step_count) - self._shadow[1])
