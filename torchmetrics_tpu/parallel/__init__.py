from torchmetrics_tpu.parallel.quantized import (  # noqa: F401
    quantized_all_gather,
    quantized_sync,
)
from torchmetrics_tpu.parallel.sync import (  # noqa: F401
    Reduction,
    class_reduce,
    gather_all_tensors,
    host_sync_value,
    in_named_axis_context,
    reduce,
    sync_states,
    sync_value,
)

__all__ = [
    "Reduction",
    "class_reduce",
    "gather_all_tensors",
    "host_sync_value",
    "in_named_axis_context",
    "quantized_all_gather",
    "quantized_sync",
    "reduce",
    "sync_states",
    "sync_value",
]
