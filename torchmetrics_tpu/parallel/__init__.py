from torchmetrics_tpu.parallel.sync import (  # noqa: F401
    Reduction,
    class_reduce,
    gather_all_tensors,
    host_sync_value,
    in_named_axis_context,
    reduce,
    sync_states,
    sync_value,
)
