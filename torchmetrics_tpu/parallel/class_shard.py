"""Class-axis state sharding: the layout math + sparse routing seam.

Every placement before this module replicated a declared state per device (or
stacked it along the DATA axis in deferred mode), so a state's full class
axis had to fit on every chip — a 100k-class confusion matrix (num_classes²
f32 ≈ 40 GB) simply could not exist. This module applies the cross-replica
weight-update sharding idea (Xu et al., arXiv:2004.13336) to *metric state*:
partition a declared state along its first class/bucket axis into
``num_shards`` equal slices (docs/SHARDING.md "Class-axis state sharding"),
and route each sparse ``(index, value)`` update contribution to the shard
that owns its class range.

Layout (the ONE invariant every consumer of a class-sharded field relies on):

- a field declared dense ``(C, *rest)`` lives as a **stacked** array
  ``(S, shard_size, *rest)`` with ``shard_size = ceil(C / S)``; the padded
  tail rows of the last shard hold the reduction identity and never receive
  contributions, so folds and elementwise merges stay exact;
- shard ``s`` owns dense classes ``[s * shard_size, min((s+1) * shard_size,
  C))`` — :meth:`ClassShardLayout.bounds`;
- the dense value is always recoverable as a pure metadata reshape + trim
  (:func:`gather_dense`) — no arithmetic, no collective.

Routing (:func:`route_scatter_add`) is the ship-but-never-land trick the
session lanes use: every contribution is shipped with a shard coordinate,
and rows nobody owns (``ignore_index`` holes, quarantined-lane rows diverted
by the row screen) carry a sentinel coordinate one past the last shard so the
XLA scatter's explicit ``mode="drop"`` discards them on device. Negative
indices are remapped BEFORE the scatter — JAX scatter treats negative
indices as wrap-around (counting from the end) even in drop mode, so a raw
``-1`` sentinel would corrupt the last row instead of vanishing.

Updates therefore stay zero-collective (tools/lint_collectives.py pins this
module update-stage); ``compute()`` performs the one gather at read, exactly
like the deferred reduce defers its fold. The data-axis machinery composes
on TOP of the class stack: deferred mode adds its leading shard axis over
``(S, shard_size, *rest)`` and every fold stays elementwise.
"""
from __future__ import annotations

import os
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.parallel.sync import Reduction, reduction_identity
from torchmetrics_tpu.utils.exceptions import TopologyMismatchError

__all__ = [
    "CLASS_SHARDABLE_REDUCTIONS",
    "STATE_SHARDINGS",
    "STATE_SHARDING_ENV",
    "ClassShardLayout",
    "ClassShardMirror",
    "add_dense",
    "default_state_sharding",
    "default_class_shards",
    "gather_dense",
    "route_scatter_add",
    "shard_layout",
    "stack_dense",
]

#: valid ``state_sharding`` policies (metric ctor knob / ``add_state`` arg)
STATE_SHARDINGS = ("replicated", "class_axis")

#: process-wide default policy for eligible states (docs/SHARDING.md)
STATE_SHARDING_ENV = "TORCHMETRICS_TPU_STATE_SHARDING"

#: reduction families whose identity pads + elementwise merges make the
#: stacked class layout exact (the same families reshard.py can re-split)
CLASS_SHARDABLE_REDUCTIONS = ("sum", "mean", "max", "min")


def default_state_sharding() -> str:
    """The process-wide default ``state_sharding`` policy, from
    ``TORCHMETRICS_TPU_STATE_SHARDING`` (``replicated`` when unset). The
    policy only ever applies to *eligible* states — fixed-shape array states
    of rank >= 1 with a reduction in :data:`CLASS_SHARDABLE_REDUCTIONS`;
    everything else silently stays replicated (mirroring how integer states
    always sync exact regardless of ``sync_precision``)."""
    value = os.environ.get(STATE_SHARDING_ENV, "replicated").strip().lower()
    if value not in STATE_SHARDINGS:
        raise ValueError(
            f"{STATE_SHARDING_ENV} must be one of {STATE_SHARDINGS}, got {value!r}"
        )
    return value


def default_class_shards() -> int:
    """Default shard count for class-axis layouts: one shard per local
    device, so placing the stacked axis on the mesh gives each chip exactly
    its slice (the per-device state-bytes ≈ dense/S claim of the bench)."""
    return int(jax.local_device_count())


class ClassShardLayout(NamedTuple):
    """Descriptor of one class-sharded field: ``num_classes`` dense rows
    split into ``num_shards`` slices of ``shard_size = ceil(C / S)`` rows,
    padded to ``padded_classes = S * shard_size``."""

    num_classes: int
    num_shards: int

    @property
    def shard_size(self) -> int:
        return -(-self.num_classes // self.num_shards)

    @property
    def padded_classes(self) -> int:
        return self.num_shards * self.shard_size

    def bounds(self, shard: int) -> Tuple[int, int]:
        """Dense class interval ``[start, stop)`` owned by ``shard`` (clipped
        to ``num_classes``; trailing shards past the data own nothing)."""
        if not 0 <= shard < self.num_shards:
            raise ValueError(f"shard must be in [0, {self.num_shards}), got {shard}")
        start = min(shard * self.shard_size, self.num_classes)
        stop = min(start + self.shard_size, self.num_classes)
        return start, stop


def shard_layout(num_classes: int, num_shards: int) -> ClassShardLayout:
    """Validated :class:`ClassShardLayout` constructor."""
    if int(num_classes) < 1:
        raise ValueError(f"num_classes must be >= 1, got {num_classes}")
    if int(num_shards) < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return ClassShardLayout(int(num_classes), int(num_shards))


def _check_stacked(stacked: Any, layout: ClassShardLayout) -> None:
    """Raise (flighted, reshard domain) when an array does not carry
    ``layout``'s stacked shape — the one corruption the pure reshapes below
    would otherwise silently misinterpret."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    shape = tuple(getattr(stacked, "shape", ()))
    if len(shape) < 2 or shape[0] != layout.num_shards or shape[1] != layout.shard_size:
        raise obs.flighted(
            TopologyMismatchError(
                f"class-sharded state has shape {shape} but the layout expects"
                f" ({layout.num_shards}, {layout.shard_size}, ...) —"
                f" {layout.num_classes} classes over {layout.num_shards} shards"
            ),
            domain="reshard",
        )


def stack_dense(dense: Any, layout: ClassShardLayout, pad_value: Any = None) -> jnp.ndarray:
    """Split a dense ``(C, *rest)`` value into the stacked class layout
    ``(S, shard_size, *rest)``, padding the tail with ``pad_value`` (the
    reduction identity for live states; 0 for additive contributions)."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    arr = jnp.asarray(dense)
    if arr.ndim < 1 or arr.shape[0] != layout.num_classes:
        raise obs.flighted(
            TopologyMismatchError(
                f"dense value has shape {tuple(arr.shape)} but the layout expects"
                f" ({layout.num_classes}, ...)"
            ),
            domain="reshard",
        )
    pad = layout.padded_classes - layout.num_classes
    if pad:
        fill = jnp.full((pad,) + arr.shape[1:], 0 if pad_value is None else pad_value, arr.dtype)
        arr = jnp.concatenate([arr, fill], axis=0)
    return arr.reshape((layout.num_shards, layout.shard_size) + arr.shape[1:])


def gather_dense(stacked: Any, layout: ClassShardLayout) -> jnp.ndarray:
    """The one read-point gather: stacked ``(S, shard_size, *rest)`` back to
    dense ``(C, *rest)`` — a pure metadata reshape + trim, no arithmetic."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    arr = jnp.asarray(stacked)
    _check_stacked(arr, layout)
    with obs.device_span(obs.SPAN_CLASS_ROUTE):
        return arr.reshape((layout.padded_classes,) + arr.shape[2:])[: layout.num_classes]


def route_scatter_add(
    stacked: Any,
    class_idx: Any,
    values: Any,
    inner_idx: Optional[Any] = None,
    *,
    layout: ClassShardLayout,
) -> jnp.ndarray:
    """Route sparse update contributions into the shards owning them.

    ``class_idx`` (any shape, flattened) carries one dense class index per
    contribution; ``values`` (same count) the amount to accumulate. With
    ``inner_idx`` the field's trailing axes are treated as one flattened
    inner dimension and each contribution lands at ``[class, inner]`` (a
    confusion-matrix cell); without it the field must be ``(C,)`` per shard
    row (a per-class counter).

    Contributions whose class index falls outside ``[0, num_classes)`` —
    ``ignore_index`` holes encoded as ``-1``, rows a quarantine screen
    diverted, garbage labels — are remapped to a sentinel coordinate one past
    the last shard and dropped ON DEVICE by the scatter's ``mode="drop"``:
    they ship but never land, so the routed update stays branch-free and
    zero-collective. (The remap is load-bearing: JAX scatter wraps negative
    indices even in drop mode, so ``-1`` would otherwise hit the last row.)
    """
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    arr = jnp.asarray(stacked)
    _check_stacked(arr, layout)
    idx = jnp.asarray(class_idx).reshape(-1).astype(jnp.int32)
    vals = jnp.asarray(values).reshape(-1).astype(arr.dtype)
    owned = (idx >= 0) & (idx < layout.num_classes)
    # sentinel = padded_classes => shard coordinate S (one past the stack) —
    # genuinely out of bounds, so mode="drop" discards the whole contribution
    safe = jnp.where(owned, idx, layout.padded_classes)
    shard_of = safe // layout.shard_size
    local = safe % layout.shard_size
    obs.counter_inc("shards.routed_updates")
    with obs.device_span(obs.SPAN_CLASS_ROUTE):
        if inner_idx is None:
            if arr.ndim != 2:
                raise obs.flighted(
                    TopologyMismatchError(
                        f"route without inner_idx needs a (S, shard_size) state,"
                        f" got shape {tuple(arr.shape)}"
                    ),
                    domain="reshard",
                )
            return arr.at[shard_of, local].add(vals, mode="drop")
        inner = jnp.asarray(inner_idx).reshape(-1).astype(jnp.int32)
        flat = arr.reshape(arr.shape[:2] + (-1,))
        out = flat.at[shard_of, local, inner].add(vals, mode="drop")
        return out.reshape(arr.shape)


def add_dense(stacked: Any, dense: Any, layout: ClassShardLayout) -> jnp.ndarray:
    """Accumulate a DENSE ``(C, *rest)`` additive contribution into the
    stacked layout (the stat-scores family emits dense per-class vectors):
    zero-pad, reshape into the stack, add elementwise. Pad rows receive 0,
    so the tail stays at the additive identity. Zero-collective."""
    from torchmetrics_tpu import obs  # deferred: sync.py's import-cycle note applies

    arr = jnp.asarray(stacked)
    _check_stacked(arr, layout)
    obs.counter_inc("shards.routed_updates")
    with obs.device_span(obs.SPAN_CLASS_ROUTE):
        return arr + stack_dense(dense, layout, pad_value=0).astype(arr.dtype)


def identity_pad_value(reduction: Reduction, dtype: Any) -> Any:
    """The pad value a live class-sharded state's tail rows carry: the
    declared reduction's identity (0 for sum/mean, ∓inf for max/min), so a
    later fold or merge over the stack cannot see the padding."""
    ident = reduction_identity(reduction, dtype)
    return 0 if ident is None else ident


def _assemble_host(v: Any):
    """Full host copy of a (possibly sharded) array, assembled shard by
    shard. ``np.array`` on a class-sharded operand routes through a gathered
    relayout (~3-4x slower than the raw copy on the CPU harness); writing
    each addressable shard's local buffer into a preallocated host array is
    a plain memcpy per shard. Deduped by shard index so replicated arrays
    are copied once, with ``np.array`` as the fallback for anything not
    fully addressable."""
    import numpy as np

    arr = jnp.asarray(v)
    try:
        if not arr.is_fully_addressable:
            return np.array(arr)
        shards = arr.addressable_shards
    except (AttributeError, TypeError):
        return np.array(arr)
    if not shards or arr.ndim == 0:
        return np.array(arr)
    out = np.empty(arr.shape, np.dtype(arr.dtype))
    seen = set()
    for sh in shards:
        key = tuple(
            (s.start, s.stop, s.step) if isinstance(s, slice) else s for s in sh.index
        )
        if key in seen:
            continue
        seen.add(key)
        out[sh.index] = np.asarray(sh.data)
    return out


class _ClassMirrorRecovery:
    """Handle the executor holds across one donating class-sharded dispatch:
    ``as_state()`` reinstalls the mirrored pre-call state if the dispatch
    dies (mirroring ``quarantine._MirrorRecovery`` at cell granularity)."""

    def __init__(self, mirror: "ClassShardMirror") -> None:
        self._mirror = mirror

    def as_state(self):
        data = self._mirror._mirror or {}
        out = {k: jnp.asarray(v) for k, v in data.items()}
        # a restore means the dispatch died: the commit stream is no longer
        # one-snapshot-per-commit, so the next snapshot must rebuild fully
        self._mirror._count = None
        self._mirror._pending = None
        return out

    def materialize(self):
        """Detached host copy for the Autosaver recovery-reuse seam
        (host-to-host memcpy, zero extra device sync); None when cold."""
        data = self._mirror._mirror
        if data is None:
            return None
        import numpy as np

        return {k: np.array(v) for k, v in data.items()}


class ClassShardMirror:
    """Incremental host-side mirror of stacked class-sharded state, at CELL
    granularity — the laned ``LaneStateMirror`` idea applied to the class
    axis.

    A 50k-class sharded confusion matrix is ~10 GB of stacked state; the
    executor's classic recovery snapshot copied ALL of it to host before
    every donating call. But one update round touches at most batch-size
    distinct ``(target_class, pred_class)`` cells, so the mirror folds
    forward only the flat cells the previous round touched (one rows-sized
    device gather) and pays the full host copy only when the incremental
    chain is provably broken: first use, a commit that bypassed the snapshot
    hook (update-counter mismatch), or a layout change (shape/dtype
    mismatch).

    ``cells`` maps each state field to the FLAT element indices (into
    ``state[field].reshape(-1)``) the about-to-run round will touch; the
    metric derives them host-side from its update args
    (``Metric._touched_class_cells``).
    """

    def __init__(self) -> None:
        self._mirror = None  # field -> host np array, stacked shape
        self._pending = None  # field -> flat np.int64 cell indices of the last round
        self._count = None  # update_count at the last snapshot
        self.stats = {"rebuilds": 0, "incremental": 0}

    def invalidate(self) -> None:
        self._mirror = None
        self._pending = None
        self._count = None

    def _chain_intact(self, state, update_count: int) -> bool:
        import numpy as np

        if self._mirror is None or self._count is None:
            return False
        if update_count != self._count + 1:
            return False  # a commit happened without a snapshot: mirror is stale
        if set(self._mirror) != set(state):
            return False
        for k, v in state.items():
            ref = self._mirror[k]
            if tuple(ref.shape) != tuple(v.shape) or ref.dtype != np.dtype(v.dtype):
                return False
        return True

    def verify(self, state, update_count: int) -> bool:
        """Bit-exact coherence audit of the mirror against the live stacked
        state it claims to equal (integrity.py "mirror" surface): valid while
        the update count still matches the last snapshot's. A diverged mirror
        is invalidated (next snapshot pays one full rebuild instead of
        serving corrupt recovery cells) with a breadcrumb; returns False on
        divergence. Blocking — audit/read-point use only."""
        import numpy as np

        if self._mirror is None or self._count != int(update_count):
            return True  # cold or out-of-phase: nothing coherent to audit
        from torchmetrics_tpu import obs
        from torchmetrics_tpu.integrity import host_leaf_fingerprint

        bad = None
        for k, ref in self._mirror.items():
            live = state.get(k)
            if live is None or tuple(ref.shape) != tuple(jnp.shape(live)):
                bad = k
                break
            if not np.array_equal(
                host_leaf_fingerprint(ref), host_leaf_fingerprint(_assemble_host(live))
            ):
                bad = k
                break
        if bad is None:
            return True
        self.invalidate()
        obs.counter_inc("integrity.mirror_rebuilds")
        obs.fault_breadcrumb(
            "mirror_divergence",
            domain="integrity",
            data={"mirror": "ClassShardMirror", "field": bad, "update_count": int(update_count)},
        )
        return False

    def snapshot(self, state, cells, update_count: int) -> _ClassMirrorRecovery:
        """Bring the mirror up to the pre-dispatch state (folding in the
        previous round's touched cells) and register this round's cells for
        the next fold. The ``np.array``/``np.asarray`` here are THE
        deliberate recovery host copies — cells-sized on the warm path,
        state-sized only on a chain break."""
        import numpy as np

        if self._chain_intact(state, int(update_count)):
            for k, pend in (self._pending or {}).items():
                if pend.size:
                    # gather via unraveled multi-dim indices: a flat
                    # ``reshape(-1)`` on a class-sharded operand materializes
                    # the whole re-laid-out state before the take (a full
                    # cross-shard relayout per call); the multi-dim gather
                    # stays cells-sized end to end
                    arr = jnp.asarray(state[k])
                    if arr.ndim == 0:
                        self._mirror[k][...] = np.asarray(arr)
                    else:
                        multi = np.unravel_index(pend, arr.shape)
                        vals = np.asarray(arr[tuple(jnp.asarray(ix) for ix in multi)])
                        self._mirror[k].reshape(-1)[pend] = vals
            self.stats["incremental"] += 1
        else:
            self._mirror = {k: _assemble_host(v) for k, v in state.items()}
            self.stats["rebuilds"] += 1
        pending = {}
        for k, idx in cells.items():
            flat = np.unique(np.asarray(idx).reshape(-1).astype(np.int64))
            pending[k] = flat[(flat >= 0) & (flat < self._mirror[k].size)]
        self._pending = pending
        self._count = int(update_count)
        return _ClassMirrorRecovery(self)
