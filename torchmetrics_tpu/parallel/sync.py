"""Distributed state synchronisation — the TPU-native communication backend.

Replaces the reference's torch.distributed path (utilities/distributed.py:97-147:
barrier → all_gather(shapes) → padded all_gather → trim) with XLA collectives over
named mesh axes:

- ``sum/mean/max/min`` reductions become single ``lax.psum/pmean/pmax/pmin`` ops —
  O(|state|) over ICI instead of the reference's O(world·|state|) gather+reduce.
- ``cat``/``None`` reductions become ``lax.all_gather(..., tiled=True)``; shapes are
  static under jit so no shape-gather or padding round-trip is ever needed.
- Multi-host (DCN) outside jit uses ``multihost_utils.process_allgather``.

A state's reduction is declared once via ``add_state(dist_reduce_fx=...)`` and that
single declaration drives local merging, in-trace collectives and host-side sync —
the PartitionSpec-aware generalisation of the reference's ``dist_reduce_fx``.

Class-axis sharded states (``add_state(state_sharding="class_axis")``,
``parallel/class_shard.py``) pass through this module UNCHANGED: the stacked
``(S, shard_size, *rest)`` layout commutes with every eligible elementwise
reduction (sum/mean/max/min — the eligibility rule exists precisely so this
holds), and the identity-padded tail rows reduce to the identity, so syncing
the stacked form across hosts equals stacking the synced dense form. Their
own routing/gather path adds ZERO collectives (``tools/lint_collectives.py``
scans every function in class_shard.py) — the one reduce here stays the only
rendezvous.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

Reduction = Union[str, Callable, None]

_VALID_REDUCTIONS = ("sum", "mean", "max", "min", "cat")

#: env var holding the fleet-wide default host-sync bound (seconds, float)
SYNC_TIMEOUT_ENV = "TORCHMETRICS_TPU_SYNC_TIMEOUT"

#: env var holding the fleet-wide default reduction policy ("step" | "deferred")
REDUCE_POLICY_ENV = "TORCHMETRICS_TPU_REDUCE"

REDUCE_POLICIES = ("step", "deferred")

#: valid ``on_sync_failure`` degradation policies for the bounded multi-host
#: sync path (docs/ROBUSTNESS.md): propagate, keep local-only state, retry
#: with backoff, or serve the last successfully-synced compute value with
#: staleness metadata (``quarantine.DegradedValue``)
SYNC_FAILURE_POLICIES = ("raise", "local", "retry", "last_good")


def default_reduce_policy() -> str:
    """The environment-configured reduction policy (``TORCHMETRICS_TPU_REDUCE``).

    ``"step"`` (default) keeps the per-step collective semantics; ``"deferred"``
    accumulates locally and applies each state's declared ``dist_reduce_fx``
    exactly once, at ``compute()``/``sync()`` time (docs/SHARDING.md).
    """
    raw = os.environ.get(REDUCE_POLICY_ENV, "").strip().lower()
    if not raw:
        return "step"
    if raw not in REDUCE_POLICIES:
        raise ValueError(f"{REDUCE_POLICY_ENV} must be one of {REDUCE_POLICIES}, got {raw!r}")
    return raw


def default_sync_timeout() -> Optional[float]:
    """The environment-configured host-sync timeout, or None (unbounded)."""
    raw = os.environ.get(SYNC_TIMEOUT_ENV, "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(f"{SYNC_TIMEOUT_ENV} must be a number of seconds, got {raw!r}")
    return value if value > 0 else None


def _process_allgather(value: Any) -> Any:
    """The raw DCN collective — a module-level seam so the fault-injection
    harness (testing/faults.py) can hang or break it without a real cluster."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(value)


class _GatherWorker:
    """One dedicated DAEMON thread serving bounded gathers.

    The previous implementation parked a non-daemon ``ThreadPoolExecutor``
    worker on every abandoned gather: under repeated ``on_sync_failure="local"``
    degradation against a dead peer, each timeout leaked one live worker — and
    because pool threads are non-daemon, a single permanently-hung rendezvous
    wedged interpreter shutdown at the atexit join. This worker is daemon (a
    parked gather can never block process exit), and retirement is
    deterministic: a timed-out worker is marked retired, exits the moment its
    abandoned gather finally returns (or never runs again if it doesn't), and
    the module respawns exactly one replacement lazily.
    """

    def __init__(self) -> None:
        import queue

        self._jobs: Any = queue.Queue()
        self._retired = False
        self._thread = threading.Thread(target=self._loop, name="tm_tpu_sync", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return  # retired while idle
            fn, value, box, done = job
            try:
                box["ok"] = fn(value)
            except BaseException as err:
                # not swallowed: _gather_with_timeout re-raises this on the
                # waiting thread (unless the waiter already timed out and
                # abandoned the gather, in which case nobody is listening)
                box["err"] = err
                from torchmetrics_tpu.utils.prints import rank_zero_debug

                rank_zero_debug(f"tm_tpu gather worker: {type(err).__name__}: {err}")
            done.set()
            if self._retired:
                return  # abandoned mid-gather: the result arrived too late to matter

    def usable(self) -> bool:
        return not self._retired and self._thread.is_alive()

    def submit(self, fn: Callable, value: Any):
        box: Dict[str, Any] = {}
        done = threading.Event()
        self._jobs.put((fn, value, box, done))
        return box, done

    def retire(self) -> None:
        """Mark retired; an idle worker exits now, a parked one exits as soon
        as its abandoned gather clears."""
        self._retired = True
        self._jobs.put(None)


#: the shared worker for bounded gathers — one daemon thread serves every
#: successful sync; retired (and lazily replaced) when a timeout leaves it
#: parked on an abandoned gather, so repeated timeouts never accumulate live
#: workers and a permanently-hung rendezvous cannot block interpreter exit
_gather_pool: Optional[_GatherWorker] = None


def _gather_with_timeout(value: Any, timeout: Optional[float]) -> Any:
    """``process_allgather`` bounded by ``timeout`` seconds.

    A hung collective (the classic multi-host failure mode: one process died
    mid-epoch and the rest block forever inside the rendezvous) surfaces as
    :class:`SyncTimeoutError` instead of a silent hang. The abandoned gather
    thread cannot be cancelled — it parks (daemon, self-retiring) until the
    runtime gives up. A bounded retry against a *transiently* dead peer is
    reasonable (``on_sync_failure="retry"``, io/retry.py) — each attempt costs
    at most one parked worker — but a timeout that repeats is this process's
    cue to checkpoint local state (io/checkpoint.py) and exit.
    """
    # deferred: utils/__init__ itself imports from this module (reduce/class_reduce),
    # so obs (whose exporters pull in utils.prints) cannot be imported at module scope
    from torchmetrics_tpu import obs

    if timeout is None:
        with obs.span(obs.SPAN_SYNC_GATHER, bounded=False):
            return _process_allgather(value)
    global _gather_pool

    from torchmetrics_tpu.utils.exceptions import SyncTimeoutError

    with obs.span(obs.SPAN_SYNC_GATHER, timeout_s=timeout):
        worker = _gather_pool
        if worker is None or not worker.usable():
            worker = _GatherWorker()
            _gather_pool = worker
        box, done = worker.submit(_process_allgather, value)
        if not done.wait(timeout):
            # the worker is now parked on the abandoned gather: retire it so the
            # next sync starts with a free worker instead of queueing behind it
            _gather_pool = None
            worker.retire()
            obs.counter_inc("sync.timeouts")
            raise obs.flighted(
                SyncTimeoutError(
                    f"multi-host state sync (process_allgather) did not complete within {timeout}s"
                ),
                domain="sync",
                kind="sync_timeout",
                timeout_s=timeout,
            )
        if "err" in box:
            obs.counter_inc("sync.gather_errors")
            raise box["err"]
        return box["ok"]


def in_named_axis_context(axis_name: Union[str, Sequence[str]]) -> bool:
    """True when called inside a pmap/shard_map/vmap trace binding ``axis_name``.

    A sequence of names (the multi-axis data×sequence case, SURVEY §5) requires
    every listed axis to be bound.
    """
    if isinstance(axis_name, (tuple, list)):
        return len(axis_name) > 0 and all(in_named_axis_context(a) for a in axis_name)
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:  # jax raises NameError for an unbound axis; anything else is a real bug
        return False


def sync_value(value: Any, reduction: Reduction, axis_name: Union[str, Sequence[str]]) -> Any:
    """Reduce one state value across a named mesh axis inside a traced context.

    ``value`` may be an Array (fixed-shape accumulator) or a list of Arrays
    (growing accumulator — pre-concatenated like reference metric.py:437-439).
    """
    is_list = isinstance(value, (list, tuple))
    if is_list:
        if len(value) == 0:
            return value
        value = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)

    if reduction == "sum":
        out = lax.psum(value, axis_name)
    elif reduction == "mean":
        out = lax.pmean(value, axis_name)
    elif reduction == "max":
        out = lax.pmax(value, axis_name)
    elif reduction == "min":
        out = lax.pmin(value, axis_name)
    elif reduction == "cat" or reduction is None or callable(reduction):
        gathered = lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0)
        if reduction == "cat":
            out = gathered.reshape((-1,) + gathered.shape[2:])
        elif callable(reduction):
            out = reduction(gathered)
        else:
            out = gathered  # stacked per-rank, mirroring dist_reduce_fx=None
    else:
        raise ValueError(f"Unknown reduction {reduction!r}")

    return [out] if is_list else out


def _nbytes_of(value: Any) -> int:
    """Static payload bytes of one state value (lists sum their elements) —
    trace-time metadata for the ``sync.bytes_on_wire`` counter."""
    if isinstance(value, (list, tuple)):
        return sum(_nbytes_of(v) for v in value)
    arr = jnp.asarray(value)
    return int(arr.size) * int(jnp.dtype(arr.dtype).itemsize)


def sync_states(
    states: Dict[str, Any],
    reductions: Dict[str, Reduction],
    axis_name: Union[str, Sequence[str]],
    qspecs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Apply the declared collectives to every state field. Pure; safe under jit.

    Fields sharing a ``sum/mean/max/min`` reduction (and dtype) are ravelled
    into ONE flat vector and reduced by a single collective, then split back —
    a metric with K scalar counters costs one rendezvous, not K (``lax.psum``
    on a pytree binds one primitive PER LEAF, so leaf-level fusion must be done
    by hand; the concat/split is pure data movement XLA fuses away). The
    stat-scores tp/fp/tn/fn quartet syncs as a single psum of a 4-vector.
    Lists and ``cat``/callable/None reductions keep the per-field
    :func:`sync_value` path.

    ``qspecs`` (``Metric._sync_qspecs()``) maps field names to their resolved
    quantization spec: ``None`` = exact, ``(bits, block)`` = route through the
    block-quantized collective (parallel/quantized.py). The spec JOINS the
    fusion group key — quantized fields fuse only with same-``(bits, block)``
    peers, never with exact ones, so one policy can never perturb the other's
    arithmetic. Integer/bool fields always take the exact path regardless of
    their spec (the encoder additionally refuses them, by construction).

    Counter semantics (like the ops/kernels.py dispatch counters): under jit
    this body runs at trace time, so ``sync.bytes_on_wire`` /
    ``sync.quantized_reduces`` count per *traced* collective — one bump per
    compiled executable per sync site, attributing which path (and payload
    size) was built.
    """
    from torchmetrics_tpu import obs  # deferred: see _gather_with_timeout
    from torchmetrics_tpu.parallel import quantized as _q

    fused_ops = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax, "min": lax.pmin}
    grouped: Dict[Any, List[Any]] = {}
    qgrouped: Dict[Any, List[Any]] = {}
    out: Dict[str, Any] = {}
    qspecs = qspecs or {}
    for name, value in states.items():
        fx = reductions.get(name)
        q = qspecs.get(name)
        if fx in fused_ops and not isinstance(value, (list, tuple)):
            arr = jnp.asarray(value)
            if q is not None and jnp.issubdtype(arr.dtype, jnp.floating):
                qgrouped.setdefault((fx, arr.dtype, tuple(q)), []).append((name, arr))
                continue
            if arr.dtype != jnp.bool_:
                grouped.setdefault((fx, arr.dtype), []).append((name, arr))
                continue
        if q is not None and fx in ("cat", None) and not callable(fx):
            # quantized gather for float cat/None states (growing accumulators)
            payload = value
            is_list = isinstance(payload, (list, tuple))
            if not (is_list and len(payload) == 0):
                if is_list:
                    payload = jnp.concatenate([jnp.atleast_1d(v) for v in payload], axis=0)
                payload = jnp.atleast_1d(jnp.asarray(payload))
                if jnp.issubdtype(payload.dtype, jnp.floating):
                    bits, block = q
                    obs.counter_inc("sync.quantized_reduces")
                    obs.counter_inc(
                        "sync.bytes_on_wire",
                        _q.quantized_wire_bytes(int(payload.size), bits, block)["total"],
                    )
                    gathered = _q.quantized_all_gather(payload, axis_name, bits=bits, block_size=block)
                    res = gathered.reshape((-1,) + gathered.shape[2:]) if fx == "cat" else gathered
                    out[name] = [res] if is_list else res
                    continue
        out[name] = sync_value(value, fx, axis_name)
        obs.counter_inc("sync.bytes_on_wire", _nbytes_of(value))
    for (fx, _), items in grouped.items():
        obs.counter_inc("sync.bytes_on_wire", sum(_nbytes_of(arr) for _, arr in items))
        if len(items) == 1:
            name, arr = items[0]
            out[name] = fused_ops[fx](arr, axis_name)
            continue
        flat = jnp.concatenate([arr.ravel() for _, arr in items])
        reduced = fused_ops[fx](flat, axis_name)
        offsets = np.cumsum([arr.size for _, arr in items])[:-1]
        for (name, arr), part in zip(items, jnp.split(reduced, offsets)):
            out[name] = part.reshape(arr.shape)
    for (fx, _, (bits, block)), items in qgrouped.items():
        # the quantized analogue of the fused psum: ONE concat-ravel, ONE
        # block-encode, one gather of codes + scales per (reduction, dtype,
        # bits, block) group, dequantize-and-accumulate, split back
        flat = items[0][1].ravel() if len(items) == 1 else jnp.concatenate([arr.ravel() for _, arr in items])
        obs.counter_inc("sync.quantized_reduces")
        obs.counter_inc(
            "sync.bytes_on_wire", _q.quantized_wire_bytes(int(flat.size), bits, block)["total"]
        )
        reduced = _q.quantized_all_reduce(flat, axis_name, reduction=fx, bits=bits, block_size=block)
        offsets = np.cumsum([arr.size for _, arr in items])[:-1]
        for (name, arr), part in zip(items, jnp.split(reduced, offsets)):
            out[name] = part.reshape(arr.shape)
    return out


def reduction_identity(reduction: Reduction, dtype: Any) -> Optional[Any]:
    """The identity element of a declared ``dist_reduce_fx`` for ``dtype`` —
    the value a masked-out contributor (an inactive/padded session lane, a
    hole in a ragged gather) must carry so it cannot perturb the fold:

    - ``sum``/``mean``/``cat``/``None``: 0 (mean folds divide by the *active*
      count, so the masked slot only needs to vanish from the numerator),
    - ``max``: ``-inf`` for floats, the dtype's minimum for ints, False for bool,
    - ``min``: ``+inf`` for floats, the dtype's maximum for ints, True for bool,
    - callables: ``None`` — a custom reduction has no derivable identity; the
      caller must mask structurally (drop the contributor) instead.
    """
    dtype = jnp.dtype(dtype)
    if callable(reduction):
        return None
    if reduction in ("max", "min"):
        lo = reduction == "max"
        if dtype == jnp.bool_:
            return jnp.asarray(not lo, dtype)
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(-jnp.inf if lo else jnp.inf, dtype)
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.min if lo else info.max, dtype)
    return jnp.zeros((), dtype)


def reduce_stacked(gathered: Any, reduction: Reduction) -> Any:
    """Collapse the leading rank/shard axis of a stacked value per the declared
    reduction — the shared read-point fold behind :func:`host_sync_value` (the
    post-allgather reduce) and :func:`fold_sharded_states` (the out-of-mesh
    deferred reduce).

    ``gathered`` is reduced as-is (np OR jnp): single-process
    ``process_allgather`` returns scalars 0-d, which numpy's legacy
    out-of-bounds-axis tolerance reduces as a no-op — coercing to jnp here
    would turn that path into a ValueError."""
    if reduction == "sum":
        return gathered.sum(0)
    if reduction == "mean":
        return gathered.mean(0)
    if reduction == "max":
        return gathered.max(0)
    if reduction == "min":
        return gathered.min(0)
    if reduction == "cat":
        return gathered.reshape((-1,) + gathered.shape[2:])
    if callable(reduction):
        return reduction(gathered)
    return gathered


def live_window_mask(head: Any, window: int) -> jnp.ndarray:
    """Boolean ``(window,)`` mask of ring slots holding LIVE windows.

    ``head`` is the (traced or concrete) monotonic window clock; slot
    ``head % window`` houses the open window and older slots wrap behind it.
    Before the clock has wrapped once (``head < window - 1``) the not-yet
    opened slots hold defaults, which are NOT the fold identity for every
    family (e.g. a ``max`` state may default to 0) — the mask lets the fold
    replace them with :func:`reduction_identity` instead. Pure traced
    arithmetic on data: advancing the head never changes a shape.
    """
    slots = jnp.arange(window)
    age = jnp.mod(jnp.mod(head, window) - slots, window)
    return (head - age) >= 0


def fold_window_slots(value: Any, reduction: Reduction, live: jnp.ndarray) -> Any:
    """Collapse the leading WINDOW axis of a ring-stacked state field into the
    sliding-window aggregate, masking dead slots with the reduction identity.

    Ring slots are disjoint SEGMENTS of one accumulation stream, so the
    combine follows :func:`~torchmetrics_tpu.parallel.reshard.merge_folded`'s
    segment semantics — ``sum`` AND ``mean`` states both ADD across segments
    (the mean fold is linear over contributors, so per-window partial sums
    combine by addition exactly as an unwindowed run would have accumulated
    them); ``max``/``min`` take the masked extremum. ``cat``/``None``/callable
    families have no identity-masked fold — windows.py keeps those metrics on
    the eager per-window path and never calls this.
    """
    if callable(reduction) or reduction in ("cat", None):
        raise ValueError(
            f"fold_window_slots is undefined for {reduction!r} reductions; eager"
            " per-window states merge through Metric.merge_states instead"
        )
    ident = reduction_identity(reduction, value.dtype)
    mask = live.reshape((-1,) + (1,) * (value.ndim - 1))
    masked = jnp.where(mask, value, ident)
    if reduction in ("sum", "mean"):
        return masked.sum(0)
    if reduction == "max":
        return masked.max(0)
    return masked.min(0)


def host_sync_value(value: Any, reduction: Reduction, timeout: Optional[float] = None) -> Any:
    """Multi-host (DCN) sync outside jit via process_allgather, then local reduce.

    Only invoked when ``jax.process_count() > 1``; single-host states are already
    replicated so host sync is a no-op at the caller. ``timeout`` (seconds)
    bounds the collective — see :func:`_gather_with_timeout`; the degradation
    policy on timeout belongs to the caller (``Metric.sync``'s
    ``on_sync_failure``).
    """
    is_list = isinstance(value, (list, tuple))
    if is_list:
        if len(value) == 0:
            return value
        value = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)
    gathered = _gather_with_timeout(value, timeout)  # (world, *shape)
    out = reduce_stacked(gathered, reduction)
    return [out] if is_list else out


# ---------------------------------------------------------------------------
# Deferred reduction: sharded per-device state, reduced once at the read point
# ---------------------------------------------------------------------------
#
# The per-step-synced path pays one (fused) collective rendezvous every batch.
# Under the deferred policy, state instead lives SHARDED along the mesh data
# axis: every leaf carries a leading shard axis (size 1 inside a shard_map
# body, ``num_shards`` in the global stacked view), updates are purely local
# (zero collectives), and the declared ``dist_reduce_fx`` is applied exactly
# once — at compute()/sync() — via the same grouped-psum fusion sync_states
# already performs. See docs/SHARDING.md.


def shard_map_compat(
    body: Callable, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = False
) -> Callable:
    """``shard_map`` across jax versions: ``jax.shard_map(check_vma=...)`` on
    new releases, ``jax.experimental.shard_map(check_rep=...)`` on <=0.4.
    ``check_vma`` keeps the new-API spelling (metric sync bodies generally
    need it off: all_gather outputs are replicated but not statically
    provable)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma)


def local_accumulate_spec(states: Any, axis_name: str = "batch") -> Any:
    """PartitionSpec pytree for sharded metric state under ``shard_map``.

    Every array leaf is partitioned along ``axis_name`` on its leading shard
    axis — the in/out spec of the local-accumulation step. Use with states
    produced by :func:`init_sharded_states` (or carried out of a previous
    local step).
    """
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(axis_name), states)


def init_sharded_states(init: Any, num_shards: int) -> Any:
    """Stack a fresh (replicated) state pytree into the sharded layout: each
    leaf gains a leading shard axis of size ``num_shards``, every shard holding
    the default value (the identity element of its declared reduction)."""
    return jax.tree_util.tree_map(
        lambda v: jnp.broadcast_to(jnp.asarray(v)[None], (num_shards,) + jnp.asarray(v).shape), init
    )


def unshard_local_state(state: Any) -> Any:
    """Drop the leading shard axis inside a ``shard_map`` body (local size 1),
    yielding the plain per-device state ``functional_update`` expects."""
    return jax.tree_util.tree_map(lambda v: jnp.squeeze(jnp.asarray(v), axis=0), state)


def reshard_local_state(state: Any) -> Any:
    """Re-add the leading shard axis after a local update so the result maps
    back through the ``local_accumulate_spec`` out-spec."""
    return jax.tree_util.tree_map(lambda v: jnp.asarray(v)[None], state)


def reduce_sharded_states(
    states: Dict[str, Any],
    reductions: Dict[str, Reduction],
    axis_name: Union[str, Sequence[str]],
    qspecs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The deferred-reduction read point: apply every declared ``dist_reduce_fx``
    exactly once over locally-accumulated shards.

    Meant to run inside a ``shard_map`` body whose state in-spec is
    :func:`local_accumulate_spec`: each field arrives with its local shard axis
    (size 1), is unsharded, and the whole dict goes through
    :func:`sync_states` — so all sum-family fields of a metric (or, via
    ``MetricCollection.functional_sync``, a whole collection) still share ONE
    fused collective rendezvous. Returns replicated (reduced) states without
    the shard axis. ``qspecs`` routes marked float fields through the
    block-quantized collective (``sync_precision="quantized"``); integer
    fields stay exact regardless.
    """
    from torchmetrics_tpu import obs  # deferred: see _gather_with_timeout

    with obs.device_span(obs.SPAN_REDUCE):
        return sync_states(unshard_local_state(states), reductions, axis_name, qspecs=qspecs)


def fold_sharded_states(states: Dict[str, Any], reductions: Dict[str, Reduction]) -> Dict[str, Any]:
    """Out-of-mesh fold of a host-fetched sharded state (global stacked view,
    leading axis = num_shards): collapse the shard axis per declared reduction.

    This is what ``Metric.load_state(..., sharded=True)`` uses to re-reduce on
    demand — the same arithmetic :func:`reduce_sharded_states` performs with
    collectives, run on the gathered stack instead.
    """
    from torchmetrics_tpu import obs  # deferred: see _gather_with_timeout

    with obs.device_span(obs.SPAN_REDUCE):
        return {k: reduce_stacked(v, reductions.get(k)) for k, v in states.items()}


# ---------------------------------------------------------------------------
# Tensor-reduction helpers with reference parity (utilities/distributed.py:22-88)
# ---------------------------------------------------------------------------

def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor ('elementwise_mean' | 'sum' | 'none')."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction num/denom with class-level reduction (reference :45-88)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def gather_all_tensors(result: Array, axis_name: str = "batch") -> List[Array]:
    """API-parity shim for reference ``gather_all_tensors``: returns a per-rank list.

    Inside a traced named-axis context this is a single tiled all_gather split back
    into per-rank slices; shapes are static so the reference's ragged-pad dance
    (utilities/distributed.py:124-147) is unnecessary by construction.
    """
    gathered = lax.all_gather(result, axis_name, axis=0)
    return [gathered[i] for i in range(gathered.shape[0])]
