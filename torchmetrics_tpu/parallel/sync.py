"""Distributed state synchronisation — the TPU-native communication backend.

Replaces the reference's torch.distributed path (utilities/distributed.py:97-147:
barrier → all_gather(shapes) → padded all_gather → trim) with XLA collectives over
named mesh axes:

- ``sum/mean/max/min`` reductions become single ``lax.psum/pmean/pmax/pmin`` ops —
  O(|state|) over ICI instead of the reference's O(world·|state|) gather+reduce.
- ``cat``/``None`` reductions become ``lax.all_gather(..., tiled=True)``; shapes are
  static under jit so no shape-gather or padding round-trip is ever needed.
- Multi-host (DCN) outside jit uses ``multihost_utils.process_allgather``.

A state's reduction is declared once via ``add_state(dist_reduce_fx=...)`` and that
single declaration drives local merging, in-trace collectives and host-side sync —
the PartitionSpec-aware generalisation of the reference's ``dist_reduce_fx``.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

Reduction = Union[str, Callable, None]

_VALID_REDUCTIONS = ("sum", "mean", "max", "min", "cat")

#: env var holding the fleet-wide default host-sync bound (seconds, float)
SYNC_TIMEOUT_ENV = "TORCHMETRICS_TPU_SYNC_TIMEOUT"


def default_sync_timeout() -> Optional[float]:
    """The environment-configured host-sync timeout, or None (unbounded)."""
    raw = os.environ.get(SYNC_TIMEOUT_ENV, "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(f"{SYNC_TIMEOUT_ENV} must be a number of seconds, got {raw!r}")
    return value if value > 0 else None


def _process_allgather(value: Any) -> Any:
    """The raw DCN collective — a module-level seam so the fault-injection
    harness (testing/faults.py) can hang or break it without a real cluster."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(value)


def _gather_with_timeout(value: Any, timeout: Optional[float]) -> Any:
    """``process_allgather`` bounded by ``timeout`` seconds.

    A hung collective (the classic multi-host failure mode: one process died
    mid-epoch and the rest block forever inside the rendezvous) surfaces as
    :class:`SyncTimeoutError` instead of a silent hang. The abandoned gather
    thread cannot be cancelled — it parks until the runtime gives up — so a
    timeout should be treated as this process's cue to checkpoint local state
    and exit, not to retry in a loop.
    """
    if timeout is None:
        return _process_allgather(value)
    from concurrent.futures import ThreadPoolExecutor
    from concurrent.futures import TimeoutError as _FutTimeout

    # deferred: utils/__init__ itself imports from this module (reduce/class_reduce)
    from torchmetrics_tpu.utils.exceptions import SyncTimeoutError

    pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="tm_tpu_sync")
    try:
        fut = pool.submit(_process_allgather, value)
        try:
            return fut.result(timeout=timeout)
        except _FutTimeout:
            raise SyncTimeoutError(
                f"multi-host state sync (process_allgather) did not complete within {timeout}s"
            ) from None
    finally:
        pool.shutdown(wait=False)


def in_named_axis_context(axis_name: Union[str, Sequence[str]]) -> bool:
    """True when called inside a pmap/shard_map/vmap trace binding ``axis_name``.

    A sequence of names (the multi-axis data×sequence case, SURVEY §5) requires
    every listed axis to be bound.
    """
    if isinstance(axis_name, (tuple, list)):
        return len(axis_name) > 0 and all(in_named_axis_context(a) for a in axis_name)
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:  # jax raises NameError for an unbound axis; anything else is a real bug
        return False


def sync_value(value: Any, reduction: Reduction, axis_name: Union[str, Sequence[str]]) -> Any:
    """Reduce one state value across a named mesh axis inside a traced context.

    ``value`` may be an Array (fixed-shape accumulator) or a list of Arrays
    (growing accumulator — pre-concatenated like reference metric.py:437-439).
    """
    is_list = isinstance(value, (list, tuple))
    if is_list:
        if len(value) == 0:
            return value
        value = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)

    if reduction == "sum":
        out = lax.psum(value, axis_name)
    elif reduction == "mean":
        out = lax.pmean(value, axis_name)
    elif reduction == "max":
        out = lax.pmax(value, axis_name)
    elif reduction == "min":
        out = lax.pmin(value, axis_name)
    elif reduction == "cat" or reduction is None or callable(reduction):
        gathered = lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0)
        if reduction == "cat":
            out = gathered.reshape((-1,) + gathered.shape[2:])
        elif callable(reduction):
            out = reduction(gathered)
        else:
            out = gathered  # stacked per-rank, mirroring dist_reduce_fx=None
    else:
        raise ValueError(f"Unknown reduction {reduction!r}")

    return [out] if is_list else out


def sync_states(
    states: Dict[str, Any], reductions: Dict[str, Reduction], axis_name: Union[str, Sequence[str]]
) -> Dict[str, Any]:
    """Apply the declared collectives to every state field. Pure; safe under jit.

    Fields sharing a ``sum/mean/max/min`` reduction (and dtype) are ravelled
    into ONE flat vector and reduced by a single collective, then split back —
    a metric with K scalar counters costs one rendezvous, not K (``lax.psum``
    on a pytree binds one primitive PER LEAF, so leaf-level fusion must be done
    by hand; the concat/split is pure data movement XLA fuses away). The
    stat-scores tp/fp/tn/fn quartet syncs as a single psum of a 4-vector.
    Lists and ``cat``/callable/None reductions keep the per-field
    :func:`sync_value` path.
    """
    fused_ops = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax, "min": lax.pmin}
    grouped: Dict[Any, List[Any]] = {}
    out: Dict[str, Any] = {}
    for name, value in states.items():
        fx = reductions.get(name)
        if fx in fused_ops and not isinstance(value, (list, tuple)):
            arr = jnp.asarray(value)
            if arr.dtype != jnp.bool_:
                grouped.setdefault((fx, arr.dtype), []).append((name, arr))
                continue
        out[name] = sync_value(value, fx, axis_name)
    for (fx, _), items in grouped.items():
        if len(items) == 1:
            name, arr = items[0]
            out[name] = fused_ops[fx](arr, axis_name)
            continue
        flat = jnp.concatenate([arr.ravel() for _, arr in items])
        reduced = fused_ops[fx](flat, axis_name)
        offsets = np.cumsum([arr.size for _, arr in items])[:-1]
        for (name, arr), part in zip(items, jnp.split(reduced, offsets)):
            out[name] = part.reshape(arr.shape)
    return out


def host_sync_value(value: Any, reduction: Reduction, timeout: Optional[float] = None) -> Any:
    """Multi-host (DCN) sync outside jit via process_allgather, then local reduce.

    Only invoked when ``jax.process_count() > 1``; single-host states are already
    replicated so host sync is a no-op at the caller. ``timeout`` (seconds)
    bounds the collective — see :func:`_gather_with_timeout`; the degradation
    policy on timeout belongs to the caller (``Metric.sync``'s
    ``on_sync_failure``).
    """
    is_list = isinstance(value, (list, tuple))
    if is_list:
        if len(value) == 0:
            return value
        value = jnp.concatenate([jnp.atleast_1d(v) for v in value], axis=0)
    gathered = _gather_with_timeout(value, timeout)  # (world, *shape)
    if reduction == "sum":
        out = gathered.sum(0)
    elif reduction == "mean":
        out = gathered.mean(0)
    elif reduction == "max":
        out = gathered.max(0)
    elif reduction == "min":
        out = gathered.min(0)
    elif reduction == "cat":
        out = gathered.reshape((-1,) + gathered.shape[2:])
    elif callable(reduction):
        out = reduction(gathered)
    else:
        out = gathered
    return [out] if is_list else out


# ---------------------------------------------------------------------------
# Tensor-reduction helpers with reference parity (utilities/distributed.py:22-88)
# ---------------------------------------------------------------------------

def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor ('elementwise_mean' | 'sum' | 'none')."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction num/denom with class-level reduction (reference :45-88)."""
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def gather_all_tensors(result: Array, axis_name: str = "batch") -> List[Array]:
    """API-parity shim for reference ``gather_all_tensors``: returns a per-rank list.

    Inside a traced named-axis context this is a single tiled all_gather split back
    into per-rank slices; shapes are static so the reference's ragged-pad dance
    (utilities/distributed.py:124-147) is unnecessary by construction.
    """
    gathered = lax.all_gather(result, axis_name, axis=0)
    return [gathered[i] for i in range(gathered.shape[0])]
