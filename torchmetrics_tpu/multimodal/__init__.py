from torchmetrics_tpu.multimodal.clip_score import (  # noqa: F401
    CLIPImageQualityAssessment,
    CLIPScore,
)

__all__ = ["CLIPImageQualityAssessment", "CLIPScore"]
