"""CLIPScore and CLIP-IQA (reference multimodal/{clip_score,clip_iqa}.py).

The reference wraps HF ``CLIPModel``/``CLIPProcessor`` (torch). In this build
the model is a pluggable embedding hook — the same escape hatch the reference
exposes for BERTScore's ``user_model`` — so any flax/jax CLIP (or any joint
image-text embedder) drives the metric:

    embedding_fn(images, texts) -> (img_features (N, F), txt_features (N, F))

for CLIPScore, and for CLIP-IQA:

    image_embedding_fn(images) -> (N, F)
    text_embedding_fn(list_of_prompts) -> (P, F)

Loading pretrained CLIP weights requires network access; in offline
environments constructing without a hook raises with guidance.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.metric import Metric

_PROMPTS: Dict[str, Tuple[str, str]] = {
    "quality": ("Good photo.", "Bad photo."),
    "brightness": ("Bright photo.", "Dark photo."),
    "noisiness": ("Clean photo.", "Noisy photo."),
    "colorfullness": ("Colorful photo.", "Dull photo."),
    "sharpness": ("Sharp photo.", "Blurry photo."),
    "contrast": ("High contrast photo.", "Low contrast photo."),
    "complexity": ("Complex photo.", "Simple photo."),
    "natural": ("Natural photo.", "Synthetic photo."),
    "happy": ("Happy photo.", "Sad photo."),
    "scary": ("Scary photo.", "Peaceful photo."),
    "new": ("New photo.", "Old photo."),
    "warm": ("Warm photo.", "Cold photo."),
    "real": ("Real photo.", "Abstract photo."),
    "beautiful": ("Beautiful photo.", "Ugly photo."),
    "lonely": ("Lonely photo.", "Sociable photo."),
    "relaxing": ("Relaxing photo.", "Stressful photo."),
}


def _l2_normalize(x: Array) -> Array:
    return x / jnp.linalg.norm(x, axis=-1, keepdims=True)


def _clip_score_update(images, text, embedding_fn: Callable) -> Tuple[Array, int]:
    """Per-sample 100*cosine scores (reference functional/multimodal/clip_score.py:59-106)."""
    if not isinstance(images, (list, tuple)):
        images = jnp.asarray(images)
        if images.ndim == 3:
            images = images[None]
        images = list(images)
    else:
        images = [jnp.asarray(i) for i in images]
    if not all(i.ndim == 3 for i in images):
        raise ValueError("Expected all images to be 3d but found image that has either more or less")
    if not isinstance(text, list):
        text = [text]
    if len(text) != len(images):
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text)}"
        )
    img_features, txt_features = embedding_fn(jnp.stack(images), text)
    img_features = _l2_normalize(jnp.asarray(img_features))
    txt_features = _l2_normalize(jnp.asarray(txt_features))
    score = 100 * jnp.sum(img_features * txt_features, axis=-1)
    return score, len(text)


def clip_score(images, text, embedding_fn: Callable) -> Array:
    """Functional CLIPScore: mean 100*cosine(image, caption), floored at 0.

    Example:
        >>> from torchmetrics_tpu.functional import clip_score
        >>> import jax.numpy as jnp
        >>> def embed(images, texts):
        ...     img_f = jnp.stack([img.mean(axis=(1, 2)) for img in images])
        ...     txt_f = jnp.asarray([[len(t), t.count('a'), 1.0] for t in texts], dtype=jnp.float32)
        ...     return img_f, txt_f
        >>> imgs = (jnp.arange(2 * 3 * 8 * 8).reshape(2, 3, 8, 8) % 255) / 255.0
        >>> texts = ["a photo of a cat", "a photo of a dog"]
        >>> result = clip_score(imgs, texts, embedding_fn=embed)
        >>> round(float(result), 4)
        62.4327
    """
    score, n_samples = _clip_score_update(images, text, embedding_fn)
    return jnp.maximum(score.sum() / n_samples, 0.0)


class CLIPScore(Metric):
    """Mean CLIP image-caption alignment score (reference multimodal/clip_score.py:43-140).

    ``embedding_fn(images, texts) -> (img_features, txt_features)`` supplies the
    joint embedder — e.g. a transformers FlaxCLIPModel apply function, or any
    callable as below.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.multimodal import CLIPScore
        >>> def embed(images, texts):  # toy joint embedder
        ...     img_f = jnp.stack([img.mean(axis=(1, 2)) for img in images])
        ...     txt_f = jnp.asarray([[len(t), t.count("a"), 1.0] for t in texts], dtype=jnp.float32)
        ...     return img_f, txt_f
        >>> score = CLIPScore(embedding_fn=embed)
        >>> imgs = (jnp.arange(2 * 3 * 8 * 8).reshape(2, 3, 8, 8) % 255) / 255.0
        >>> score.update(imgs, ["a photo of a cat", "a photo of a dog"])
        >>> round(float(score.compute()), 4)
        62.4327
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    def __init__(self, embedding_fn: Optional[Callable] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if embedding_fn is None:
            raise ModuleNotFoundError(
                "CLIPScore requires an `embedding_fn(images, texts) -> (img_features, txt_features)` callable."
                " Pretrained CLIP weights cannot be fetched in this environment; pass e.g. a flax CLIP apply"
                " function (transformers FlaxCLIPModel) or any joint embedder."
            )
        self.embedding_fn = embedding_fn
        self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, images, text) -> None:
        score, n_samples = _clip_score_update(images, text, self.embedding_fn)
        self.score = self.score + score.sum(0)
        self.n_samples = self.n_samples + n_samples

    def compute(self) -> Array:
        return jnp.maximum(self.score / self.n_samples, 0.0)


def _clip_iqa_format_prompts(prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",)):
    """Expand prompt keywords / custom pairs (reference functional/multimodal/clip_iqa.py:92-140)."""
    if not isinstance(prompts, tuple):
        raise ValueError("Argument `prompts` must be a tuple containing strings or tuples of strings")
    prompts_names: List[str] = []
    prompts_list: List[str] = []
    count = 0
    for p in prompts:
        if not isinstance(p, (str, tuple)):
            raise ValueError("Argument `prompts` must be a tuple containing strings or tuples of strings")
        if isinstance(p, str):
            if p not in _PROMPTS:
                raise ValueError(
                    f"All elements of `prompts` must be one of {list(_PROMPTS.keys())} if not custom tuple prompts, got {p}."
                )
            prompts_names.append(p)
            prompts_list.extend(_PROMPTS[p])
        else:
            if len(p) != 2:
                raise ValueError("If a tuple is provided in argument `prompts`, it must be of length 2")
            prompts_names.append(f"user_defined_{count}")
            prompts_list.extend(p)
            count += 1
    return prompts_list, prompts_names


def clip_image_quality_assessment(
    images: Array,
    image_embedding_fn: Callable,
    text_embedding_fn: Callable,
    prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
    data_range: float = 1.0,
) -> Union[Array, Dict[str, Array]]:
    """CLIP-IQA: softmax over (positive, negative) prompt-anchor similarities.

    Reference functional/multimodal/clip_iqa.py: per prompt pair,
    ``softmax(100 * [sim_pos, sim_neg])[0]`` is the image's quality probability.
    """
    prompts_list, prompts_names = _clip_iqa_format_prompts(prompts)
    images = jnp.asarray(images) / float(data_range)
    img_features = _l2_normalize(jnp.asarray(image_embedding_fn(images)))
    anchors = _l2_normalize(jnp.asarray(text_embedding_fn(prompts_list)))
    logits = 100 * img_features @ anchors.T
    probs = jax.nn.softmax(logits.reshape(logits.shape[0], -1, 2), axis=-1)[:, :, 0]
    if len(prompts_names) == 1:
        return probs.squeeze()
    return {name: probs[:, i] for i, name in enumerate(prompts_names)}


class CLIPImageQualityAssessment(Metric):
    """Prompt-anchored no-reference image quality (reference multimodal/clip_iqa.py:56+).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment
        >>> iqa = CLIPImageQualityAssessment(
        ...     image_embedding_fn=lambda imgs: imgs.mean(axis=(2, 3)),
        ...     text_embedding_fn=lambda texts: jnp.asarray(
        ...         [[len(t), t.count("o"), 1.0] for t in texts], dtype=jnp.float32))
        >>> imgs = (jnp.arange(2 * 3 * 8 * 8).reshape(2, 3, 8, 8) % 255) / 255.0
        >>> iqa.update(imgs)
        >>> [round(float(x), 4) for x in iqa.compute()]
        [0.9965, 0.1062]
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        image_embedding_fn: Optional[Callable] = None,
        text_embedding_fn: Optional[Callable] = None,
        prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
        data_range: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if image_embedding_fn is None or text_embedding_fn is None:
            raise ModuleNotFoundError(
                "CLIPImageQualityAssessment requires `image_embedding_fn(images) -> (N, F)` and"
                " `text_embedding_fn(prompts) -> (P, F)` callables; pretrained CLIP weights cannot be"
                " fetched in this environment."
            )
        self.image_embedding_fn = image_embedding_fn
        self.text_embedding_fn = text_embedding_fn
        self.prompts_list, self.prompts_names = _clip_iqa_format_prompts(prompts)
        self._prompts_arg = prompts
        self.data_range = data_range
        self.add_state("probs_list", default=[], dist_reduce_fx="cat")

    def update(self, images: Array) -> None:
        probs = clip_image_quality_assessment(
            images, self.image_embedding_fn, self.text_embedding_fn, self._prompts_arg, self.data_range
        )
        if isinstance(probs, dict):
            probs = jnp.stack([probs[n] for n in self.prompts_names], axis=1)
        self.probs_list.append(jnp.atleast_2d(probs.reshape(-1, len(self.prompts_names))))

    def compute(self) -> Union[Array, Dict[str, Array]]:
        # per-image scores, as the reference returns (multimodal/clip_iqa.py compute)
        probs = jnp.concatenate(self.probs_list, axis=0)
        if len(self.prompts_names) == 1:
            return probs[:, 0].squeeze()
        return {name: probs[:, i] for i, name in enumerate(self.prompts_names)}
