"""Stateful ``Metric`` core — TPU-native redesign of reference metric.py (1,232 LoC).

Architecture (SURVEY.md §7): JAX demands pure functions under jit, so the true core
is *state-as-pytree*:

    state = metric._defaults-derived dict of jnp arrays (or lists for growing states)
    metric.functional_update(state, *batch) -> state'          # pure, jit/shard_map-safe
    metric.functional_compute(state)        -> value           # pure
    metric.merge_states(a, b)               -> state           # per-field declared reduction
    sync_states(state, reductions, axis)    -> state           # lax.psum/all_gather

The familiar stateful object (``m.update(...)``, ``m.compute()``, ``m(...)``,
operator algebra, ``reset/clone/state_dict``) is a thin host-side shell over that
pure core: attributes named in ``add_state`` are routed into the live state dict,
so subclasses read and assign ``self.tp += tp`` exactly like the reference
(metric.py:465-487) while the same ``update`` body traces cleanly when called
through the functional API inside a jitted train step.

Distributed sync: each state's ``dist_reduce_fx`` declaration drives
- local merging (``forward``'s reduce-state path, reference metric.py:399-431),
- in-trace collectives (``lax.psum/pmean/pmax/pmin/all_gather`` over a named mesh
  axis — reference metric.py:433-463 + utilities/distributed.py rebuilt as
  parallel/sync.py), and
- host-side multi-host sync (DCN process_allgather).
"""
from __future__ import annotations

import copy
import functools
import inspect
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.parallel.sync import (
    REDUCE_POLICIES,
    SYNC_FAILURE_POLICIES,
    Reduction,
    default_reduce_policy,
    default_sync_timeout,
    fold_sharded_states,
    host_sync_value,
    in_named_axis_context,
    init_sharded_states,
    local_accumulate_spec,
    sync_states,
)
from torchmetrics_tpu.parallel.quantized import (
    DEFAULT_BITS as _QUANT_DEFAULT_BITS,
    DEFAULT_BLOCK as _QUANT_DEFAULT_BLOCK,
    SYNC_PRECISIONS,
    default_sync_precision,
)
from torchmetrics_tpu.parallel.class_shard import (
    CLASS_SHARDABLE_REDUCTIONS,
    STATE_SHARDINGS,
    ClassShardLayout,
    default_class_shards,
    default_state_sharding,
    identity_pad_value,
    shard_layout as _class_shard_layout,
    stack_dense as _class_stack_dense,
)
from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.data import (
    _flatten,
    _squeeze_if_scalar,
)
from torchmetrics_tpu.utils.exceptions import (
    StateCorruptionError,
    TorchMetricsUserError,
    TorchMetricsUserWarning,
)
from torchmetrics_tpu.utils.prints import rank_zero_warn


def jit_distributed_available() -> bool:
    """Default world check (reference metric.py:45-47): multi-process JAX runtime."""
    return jax.process_count() > 1


def _async_materialize(value: Any) -> Any:
    """Worker-side ready-wait, routed through the read pipeline's sanctioned
    blocking point (ops/async_read.py ``materialize`` — this module stays
    clean under tools/lint_blocking_host_sync.py by construction)."""
    from torchmetrics_tpu.ops.async_read import materialize

    return materialize(value)


class Metric:
    """Base class for all metrics.

    Subclasses declare states in ``__init__`` via :meth:`add_state`, implement
    ``update(self, ...)`` mutating those states, and ``compute(self)`` returning the
    metric value. See reference metric.py:50 for the API this mirrors.

    Args:
        kwargs: common keyword arguments processed here (reference metric.py:113-148):

            - ``compute_on_cpu``: move list states to host after update.
            - ``dist_sync_on_step``: sync state when computing the batch value in
              ``forward``.
            - ``sync_axis``: the named mesh axis (or axes) collectives run over when
              syncing inside a traced context. Defaults to ``"batch"``.
            - ``dist_sync_fn``: override the per-state sync function
              ``(value, reduction, axis_name) -> value``.
            - ``distributed_available_fn``: override the world check.
            - ``sync_on_compute``: sync state automatically in ``compute`` (default True).
            - ``compute_with_cache``: cache the result of ``compute`` (default True).
            - ``executor``: route eager ``update``/``forward`` through the
              donated-state jitted executor (ops/executor.py). ``None`` (default)
              follows the ``TORCHMETRICS_TPU_EXECUTOR`` env flag (on unless set
              to ``0``); ``False`` restores the op-by-op eager path exactly.
            - ``sync_timeout``: bound (seconds) on the multi-host
              ``process_allgather`` sync path; ``None`` (default) follows the
              ``TORCHMETRICS_TPU_SYNC_TIMEOUT`` env var (unbounded when unset).
            - ``on_sync_failure``: what a failed/timed-out host sync does:
              ``"raise"`` (default) propagates the error with local state
              intact; ``"local"`` degrades to local-only state with a
              rank-zero warning, flagged via :attr:`last_sync_ok`;
              ``"retry"`` re-attempts the gather with capped exponential
              backoff (``sync_retries`` / ``TORCHMETRICS_TPU_SYNC_RETRIES``
              attempts, io/retry.py) and propagates only when the budget is
              exhausted; ``"last_good"`` serves the most recent
              successfully-synced compute value instead, wrapped in a
              :class:`~torchmetrics_tpu.quarantine.DegradedValue` carrying
              staleness metadata (falling back to ``"local"`` semantics when
              no value has been cached yet).
            - ``sync_retries``: how many backed-off re-attempts
              ``on_sync_failure="retry"`` makes before giving up; ``None``
              (default) follows ``TORCHMETRICS_TPU_SYNC_RETRIES`` (3 when
              unset).
            - ``reduce``: when the declared ``dist_reduce_fx`` runs:
              ``"step"`` keeps per-step collective semantics
              (``dist_sync_on_step`` forwards sync every batch); ``"deferred"``
              accumulates locally and applies each reduction exactly once, at
              ``compute()``/``sync()`` time (docs/SHARDING.md). ``None``
              (default) follows the ``TORCHMETRICS_TPU_REDUCE`` env var
              (``"step"`` when unset).
            - ``sync_precision``: what the in-trace collectives ship for this
              metric's FLOAT states (docs/SHARDING.md "Quantized reduce"):
              ``"exact"`` keeps full-precision psum/all_gather;
              ``"quantized"`` moves int codes + per-block max-abs scales over
              the wire (4×/2× fewer payload bytes at int8/int16) with a
              documented error bound. Integer/bool states (counts, bincounts,
              the reserved update count) are ALWAYS exact regardless.
              Per-state overrides via ``add_state(..., sync_precision=...)``.
              ``None`` (default) follows ``TORCHMETRICS_TPU_SYNC_PRECISION``
              (``"exact"`` when unset).
            - ``sync_quant_bits``: code width of the quantized wire format,
              8 (int8, default) or 16 (int16).
            - ``sync_quant_block``: elements per max-abs scale block
              (default 256 — a 1.6 % f32-scale side channel).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import Metric
        >>> class SumAbsError(Metric):
        ...     def __init__(self, **kwargs):
        ...         super().__init__(**kwargs)
        ...         self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
        ...     def update(self, preds, target):
        ...         self.total = self.total + jnp.abs(preds - target).sum()
        ...     def compute(self):
        ...         return self.total
        >>> metric = SumAbsError()
        >>> metric.update(jnp.asarray([1.0, 2.0]), jnp.asarray([1.5, 2.5]))
        >>> float(metric.compute())
        1.0
    """

    __jit_unused_properties__: List[str] = ["is_differentiable"]
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None

    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    def __init__(self, **kwargs: Any) -> None:
        # internal bookkeeping set up *before* anything routes through __setattr__
        object.__setattr__(self, "_state", {})
        self._defaults: Dict[str, Any] = {}
        self._reductions: Dict[str, Reduction] = {}
        self._persistent: Dict[str, bool] = {}
        #: declared per-state sync_precision overrides (None = inherit the
        #: metric-level policy); resolution happens in _sync_qspecs
        self._sync_precisions: Dict[str, Optional[str]] = {}
        #: RESOLVED per-state placement ("replicated" | "class_axis") and the
        #: class layout of every class_axis field (parallel/class_shard.py);
        #: resolution happens at add_state time, so these never change after
        #: declaration and can key the executor cache via _trace_config
        self._state_shardings: Dict[str, str] = {}
        self._class_layouts: Dict[str, ClassShardLayout] = {}

        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(f"Expected keyword argument `dist_sync_on_step` to be an `bool` but got {self.dist_sync_on_step}")
        self.sync_axis = kwargs.pop("sync_axis", "batch")
        self.process_group = kwargs.pop("process_group", None)
        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(f"Expected keyword argument `dist_sync_fn` to be an callable function but got {self.dist_sync_fn}")
        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or jit_distributed_available
        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}")
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        if not isinstance(self.compute_with_cache, bool):
            raise ValueError(f"Expected keyword argument `compute_with_cache` to be a `bool` but got {self.compute_with_cache}")
        self._executor_enabled = kwargs.pop("executor", None)
        if self._executor_enabled is not None and not isinstance(self._executor_enabled, bool):
            raise ValueError(f"Expected keyword argument `executor` to be a `bool` but got {self._executor_enabled}")
        self.sync_timeout = kwargs.pop("sync_timeout", None)
        if self.sync_timeout is None:
            self.sync_timeout = default_sync_timeout()
        elif not isinstance(self.sync_timeout, (int, float)) or isinstance(self.sync_timeout, bool) or self.sync_timeout <= 0:
            raise ValueError(f"Expected keyword argument `sync_timeout` to be a positive number of seconds but got {self.sync_timeout}")
        self.on_sync_failure = kwargs.pop("on_sync_failure", "raise")
        if self.on_sync_failure not in SYNC_FAILURE_POLICIES:
            raise ValueError(
                f"Expected keyword argument `on_sync_failure` to be one of {SYNC_FAILURE_POLICIES}"
                f" but got {self.on_sync_failure}"
            )
        self.sync_retries = kwargs.pop("sync_retries", None)
        if self.sync_retries is not None and (
            not isinstance(self.sync_retries, int) or isinstance(self.sync_retries, bool) or self.sync_retries < 0
        ):
            raise ValueError(f"Expected keyword argument `sync_retries` to be a non-negative int but got {self.sync_retries}")
        self._last_sync_ok = True
        self.reduce_policy = kwargs.pop("reduce", None)
        if self.reduce_policy is None:
            self.reduce_policy = default_reduce_policy()
        elif self.reduce_policy not in REDUCE_POLICIES:
            raise ValueError(f"Expected keyword argument `reduce` to be one of {REDUCE_POLICIES} but got {self.reduce_policy}")
        if self.reduce_policy == "deferred" and self.dist_sync_on_step:
            raise ValueError(
                "`reduce='deferred'` defers every collective to compute()/sync() and cannot"
                " be combined with `dist_sync_on_step=True` (a per-step sync IS the step policy)"
            )
        self.sync_precision = kwargs.pop("sync_precision", None)
        if self.sync_precision is None:
            self.sync_precision = default_sync_precision()
        elif self.sync_precision not in SYNC_PRECISIONS:
            raise ValueError(
                f"Expected keyword argument `sync_precision` to be one of {SYNC_PRECISIONS}"
                f" but got {self.sync_precision}"
            )
        self.sync_quant_bits = kwargs.pop("sync_quant_bits", None)
        if self.sync_quant_bits is None:
            self.sync_quant_bits = _QUANT_DEFAULT_BITS
        elif self.sync_quant_bits not in (8, 16):
            raise ValueError(
                f"Expected keyword argument `sync_quant_bits` to be 8 or 16 but got {self.sync_quant_bits}"
            )
        self.sync_quant_block = kwargs.pop("sync_quant_block", None)
        if self.sync_quant_block is None:
            self.sync_quant_block = _QUANT_DEFAULT_BLOCK
        elif (
            not isinstance(self.sync_quant_block, int)
            or isinstance(self.sync_quant_block, bool)
            or self.sync_quant_block < 1
        ):
            raise ValueError(
                f"Expected keyword argument `sync_quant_block` to be a positive int but got {self.sync_quant_block}"
            )
        self.state_sharding = kwargs.pop("state_sharding", None)
        if self.state_sharding is None:
            self.state_sharding = default_state_sharding()
        elif self.state_sharding not in STATE_SHARDINGS:
            raise ValueError(
                f"Expected keyword argument `state_sharding` to be one of {STATE_SHARDINGS}"
                f" but got {self.state_sharding}"
            )
        self.class_shards = kwargs.pop("class_shards", None)
        if self.class_shards is None:
            self.class_shards = default_class_shards()
        elif (
            not isinstance(self.class_shards, int)
            or isinstance(self.class_shards, bool)
            or self.class_shards < 1
        ):
            raise ValueError(
                f"Expected keyword argument `class_shards` to be a positive int but got {self.class_shards}"
            )
        # deferred-reduction bookkeeping: _reduced is False while locally
        # accumulated state has a pending reduction; _pending_shards is the
        # shard count of an installed (stacked) sharded state awaiting a fold
        self._reduced = True
        self._pending_shards: Optional[int] = None
        self._last_reduce_us: Optional[float] = None
        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        self._update_signature = inspect.signature(self.update)
        self._update_fn: Callable = self.update  # raw bound method (pre-wrap)
        self._compute_fn: Callable = self.compute
        self.update: Callable = self._wrap_update(self.update)
        self.compute: Callable = self._wrap_compute(self.compute)
        self._computed: Any = None
        self._update_count: int = 0
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._dtype_convert = False

        self._cache: Optional[Dict[str, Any]] = None
        self._is_synced = False

        # donated-state executor bookkeeping (ops/executor.py): built lazily;
        # _state_escaped means some state array may be referenced outside this
        # metric (so the executor copies before donating), _state_shared means
        # the arrays are aliased by a MetricCollection compute group (the
        # collection's fused executor manages donation for the whole group).
        self._executor_obj: Optional[Any] = None
        self._state_escaped = True
        self._state_shared = False

    # ------------------------------------------------------------------ states
    def add_state(
        self,
        name: str,
        default: Union[Array, List],
        dist_reduce_fx: Reduction = None,
        persistent: bool = False,
        sync_precision: Optional[str] = None,
        state_sharding: Optional[str] = None,
    ) -> None:
        """Register a metric state (reference metric.py:195-278).

        ``default`` is either a jnp array (fixed-shape accumulator) or an empty
        list (growing accumulator). ``dist_reduce_fx`` in
        {"sum","mean","max","min","cat", None, callable} declares how the state
        merges across batches (forward), devices (mesh collectives) and hosts.

        ``sync_precision`` overrides the metric-level policy for THIS state:
        ``"exact"`` pins full-precision collectives, ``"quantized"`` opts a
        float state into the block-quantized reduce, ``None`` (default)
        inherits the metric policy. Integer/bool states are always exact no
        matter what is declared here (docs/SHARDING.md "Quantized reduce").

        ``state_sharding`` places THIS state: ``"class_axis"`` partitions the
        declared array along its first (class/bucket) axis into the metric's
        ``class_shards`` slices — it then lives as a stacked
        ``(S, ceil(C/S), *rest)`` array (parallel/class_shard.py) whose dense
        value is gathered only at the read point — ``"replicated"`` pins the
        dense layout, ``None`` (default) inherits the metric-level
        ``state_sharding`` policy. Only fixed-shape array states of rank >= 1
        with ``dist_reduce_fx`` in {"sum","mean","max","min"} are eligible:
        an explicit ``"class_axis"`` on anything else raises, while the
        inherited policy silently leaves ineligible states replicated
        (docs/SHARDING.md "Class-axis state sharding").
        """
        if not isinstance(default, (list, int, float, np.ndarray, jnp.ndarray)) and not hasattr(default, "shape"):
            raise ValueError("state variable must be a jax array or an empty list")
        if isinstance(default, list) and default:
            raise ValueError("state variable must be a jax array or an *empty* list (any data must be appended via update)")
        if dist_reduce_fx not in ("sum", "mean", "cat", "min", "max", None) and not callable(dist_reduce_fx):
            raise ValueError(
                "`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None],"
                f" got {dist_reduce_fx!r}"
            )
        if sync_precision is not None and sync_precision not in SYNC_PRECISIONS:
            raise ValueError(f"`sync_precision` must be None or one of {SYNC_PRECISIONS}, got {sync_precision!r}")
        if state_sharding is not None and state_sharding not in STATE_SHARDINGS:
            raise ValueError(
                f"`state_sharding` must be None or one of {STATE_SHARDINGS}, got {state_sharding!r}"
            )
        if isinstance(default, (int, float)):
            default = jnp.asarray(default)
        if not isinstance(default, list):
            default = jnp.asarray(default)
        # --- class-axis placement resolution (happens ONCE, at declaration):
        # eligibility = fixed-shape array, rank >= 1, identity-padded/elementwise
        # reduction family — the static pin of docs/SHARDING.md's eligibility table
        eligible = (
            not isinstance(default, list)
            and default.ndim >= 1
            and dist_reduce_fx in CLASS_SHARDABLE_REDUCTIONS
        )
        if state_sharding == "class_axis" and not eligible:
            kind = "list" if isinstance(default, list) else f"rank-{default.ndim} array"
            raise ValueError(
                f"state {name!r}: state_sharding='class_axis' requires a fixed-shape array"
                f" state of rank >= 1 with dist_reduce_fx in {CLASS_SHARDABLE_REDUCTIONS};"
                f" got a {kind} with dist_reduce_fx={dist_reduce_fx!r}"
            )
        resolved = state_sharding
        if resolved is None:
            policy = self.__dict__.get("state_sharding", "replicated")
            resolved = "class_axis" if (policy == "class_axis" and eligible) else "replicated"
        if resolved == "class_axis":
            layout = _class_shard_layout(int(default.shape[0]), int(self.class_shards))
            default = _class_stack_dense(
                default, layout, pad_value=identity_pad_value(dist_reduce_fx, default.dtype)
            )
            self._class_layouts[name] = layout
            obs.counter_inc("shards.class_sharded_states")
        self._state_shardings[name] = resolved
        self._defaults[name] = copy.deepcopy(default)
        self._reductions[name] = dist_reduce_fx
        self._persistent[name] = persistent
        self._sync_precisions[name] = sync_precision
        self._state[name] = copy.deepcopy(default)

    def _sync_qspecs(self) -> Dict[str, Optional[Tuple[int, int]]]:
        """The RESOLVED per-state quantization policy: field name →
        ``None`` (exact) or ``(bits, block)`` (block-quantized collective).

        Resolution order: the ``add_state`` override, else the metric-level
        ``sync_precision``. Non-float array states resolve to ``None``
        unconditionally — the integer-exactness guarantee (counts, bincounts,
        ``_update_count`` never round). List (growing) states resolve by
        policy; the sync engine re-checks the concrete payload's dtype at
        encode time, so an integer list still takes the exact path."""
        d = self.__dict__
        bits = d.get("sync_quant_bits", _QUANT_DEFAULT_BITS)
        block = d.get("sync_quant_block", _QUANT_DEFAULT_BLOCK)
        policy = d.get("sync_precision", "exact")
        overrides = d.get("_sync_precisions", {})
        out: Dict[str, Optional[Tuple[int, int]]] = {}
        for name, default in self._defaults.items():
            resolved = overrides.get(name) or policy
            if resolved != "quantized":
                out[name] = None
                continue
            if not isinstance(default, list) and not jnp.issubdtype(
                jnp.asarray(default).dtype, jnp.floating
            ):
                out[name] = None  # integer-exact: counts never quantize
                continue
            out[name] = (int(bits), int(block))
        return out

    def __getattr__(self, name: str) -> Any:
        # only called when normal lookup fails
        d = self.__dict__
        state = d.get("_state")
        if state is not None and name in state:
            # the returned array may now be referenced outside the metric: the
            # executor must not donate it until it produces fresh state again
            d["_state_escaped"] = True
            return state[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("higher_is_better", "is_differentiable", "full_state_update", "plot_lower_bound", "plot_upper_bound", "plot_legend_name"):
            raise RuntimeError(f"Can't change const `{name}`.")
        state = self.__dict__.get("_state")
        if state is not None and name in state:
            state[name] = value
            self.__dict__["_state_escaped"] = True
            return
        object.__setattr__(self, name, value)

    @property
    def metric_state(self) -> Dict[str, Any]:
        """Current (live) state values (reference metric.py:190-193)."""
        self.__dict__["_state_escaped"] = True
        return {attr: self._state[attr] for attr in self._defaults}

    @property
    def executor_status(self) -> Dict[str, Any]:
        """Why (and whether) this instance runs through the donated-state
        executor — a metric silently running 20× slower on the eager path is
        diagnosable from here (ISSUE 2 satellite).

        Returns ``{"enabled": bool, "engaged": bool, "fallback_reason":
        Optional[str], "stats": {...}}``: ``enabled`` reflects the resolved
        configuration (ctor arg / env flag), ``engaged`` whether any call has
        actually executed compiled, and ``fallback_reason`` the recorded cause
        when the executor stepped aside (also logged once at debug level).
        """
        from torchmetrics_tpu.ops.executor import executor_enabled_default, executor_stats
        from torchmetrics_tpu.ops.kernels import gate_snapshot

        enabled = self.__dict__.get("_executor_enabled")
        enabled = executor_enabled_default() if enabled is None else enabled
        stats = executor_stats(self)
        return {
            "enabled": enabled,
            "engaged": stats["calls"] > 0,
            "fallback_reason": None if enabled is False else stats.get("fallback_reason"),
            # deferred-reduction observability (ISSUE 3): is a reduction still
            # pending, and how long did the last reduce/sync take on this host
            "deferred_pending": self.deferred_pending,
            "last_reduce_us": self.__dict__.get("_last_reduce_us"),
            "stats": stats,
            # which body served each backend-dispatched kernel (ISSUE 11):
            # the last gate decision + per-path selection counts, so a bench
            # run can attribute its numbers to the path that actually ran.
            # Process-global — kernel selection is per-process, not per-metric
            "kernels": gate_snapshot(),
        }

    # -------------------------------------------------- compile-ahead surface
    def warmup(
        self,
        batch_specs: Any,
        forward: bool = False,
        ladder: bool = True,
        background: bool = False,
    ) -> Any:
        """Precompile the executables this metric's traffic will need, ahead
        of traffic (docs/EXECUTOR.md "Compile-ahead & persistent cache").

        ``batch_specs`` is one example batch or a sequence of them — tuples of
        arrays or ``jax.ShapeDtypeStruct`` leaves (only shapes/dtypes matter;
        zero-filled dummies are compiled and discarded, live state is never
        touched). ``ladder=True`` also warms one padded representative per
        bucket rung so ragged epoch-final batches land warm. ``forward=True``
        additionally warms the fused forward executables. With
        ``background=True`` compilation runs on a daemon thread and a
        ``WarmupHandle`` (``.wait()`` -> report) is returned; otherwise the
        report dict ``{"warmed", "already_warm", "skipped", "seconds"}``.
        Persisted-cache entries (``TORCHMETRICS_TPU_CACHE_DIR``) make warmup
        across process restarts a deserialization, not a recompile.
        """
        ex = self._get_executor()
        if ex is None:
            return {"warmed": 0, "already_warm": 0, "skipped": ["executor disabled"], "seconds": 0.0}
        return ex.warmup(batch_specs, forward=forward, ladder=ladder, background=background)

    def warmup_from_manifest(self, manifest: Any, background: bool = False) -> Any:
        """Replay a shape-profile manifest (dict from :meth:`shape_profile` or
        a path written by :meth:`save_shape_profile`): precompiles exactly the
        call shapes a previous run recorded."""
        ex = self._get_executor()
        if ex is None:
            return {"warmed": 0, "already_warm": 0, "skipped": ["executor disabled"], "seconds": 0.0}
        return ex.warmup_from_manifest(manifest, background=background)

    def shape_profile(self) -> Dict[str, Any]:
        """Replayable manifest of the call shapes this metric's executor has
        served — save it (:meth:`save_shape_profile`) so the next process can
        ``warmup_from_manifest`` before traffic arrives."""
        ex = self._get_executor()
        if ex is None:
            from torchmetrics_tpu.ops.compile_cache import PROFILE_VERSION

            return {"profile_version": PROFILE_VERSION, "owner": type(self).__name__, "specs": []}
        return ex.shape_profile()

    def save_shape_profile(self, path: str) -> str:
        """Atomically persist :meth:`shape_profile` as JSON at ``path``."""
        from torchmetrics_tpu.ops.compile_cache import save_shape_manifest

        return save_shape_manifest(path, self.shape_profile())

    def set_background_compile(self, enabled: Optional[bool]) -> None:
        """Per-instance override of stall-free background compilation (cold
        cache keys dispatch eagerly while the compile runs on a worker; see
        docs/EXECUTOR.md). ``None`` restores the ``TORCHMETRICS_TPU_BG_COMPILE``
        env default."""
        ex = self._get_executor()
        if ex is not None:
            ex.set_background_compile(enabled)

    @property
    def deferred_pending(self) -> bool:
        """True while locally-accumulated state still awaits its deferred
        reduction — either the ``reduce="deferred"`` policy has unreduced
        updates, or a sharded state was installed (``load_state(...,
        sharded=True)``) and the fold has not run yet."""
        if self.__dict__.get("_pending_shards") is not None:
            return True
        return self.__dict__.get("reduce_policy") == "deferred" and not self.__dict__.get("_reduced", True)

    @property
    def update_called(self) -> bool:
        return self._update_count > 0

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def device(self):
        """Device of the first array state (reference tracks _device via probe)."""
        for v in self._state.values():
            if isinstance(v, jnp.ndarray):
                return list(v.devices())[0]
            if isinstance(v, list) and v:
                return list(v[0].devices())[0]
        return jax.devices()[0]

    @property
    def dtype(self):
        for v in self._state.values():
            if isinstance(v, jnp.ndarray) and jnp.issubdtype(v.dtype, jnp.floating):
                return v.dtype
        return jnp.float32

    # ------------------------------------------------------------- update path
    def _get_executor(self):
        """The lazily-built donated-state executor for this instance, or None
        when disabled (``executor=False`` ctor arg or the
        ``TORCHMETRICS_TPU_EXECUTOR`` env flag)."""
        enabled = self.__dict__.get("_executor_enabled")
        if enabled is False:
            return None
        from torchmetrics_tpu.ops import executor as _executor_mod

        if enabled is None and not _executor_mod.executor_enabled_default():
            return None
        ex = self.__dict__.get("_executor_obj")
        if ex is None:
            cls = type(self)
            ex = _executor_mod.MetricExecutor(
                self,
                plain_functional=(
                    cls.functional_update is Metric.functional_update
                    and cls.functional_compute is Metric.functional_compute
                ),
                plain_forward=(
                    cls.functional_forward is Metric.functional_forward
                    and cls.merge_states is Metric.merge_states
                ),
            )
            object.__setattr__(self, "_executor_obj", ex)
        return ex

    def _trace_config(self) -> tuple:
        """Trace-affecting configuration NOT visible in the state spec.

        The executor's cross-process cache key is class + module source hash +
        state shapes/dtypes (ops/executor.py ``_owner_desc``); config that
        changes the traced computation while leaving the state layout
        unchanged (an aggregator's ``nan_strategy``, a laned wrapper's
        device-side row screen) must be surfaced here or two differently-
        configured instances could share a persisted executable. Subclass
        overrides extend ``super()._trace_config()`` — the base marker carries
        the resolved ``sync_precision`` policy, so an exact and a quantized
        instance can never share a compiled executable or a persisted cache
        entry (the policy also joins the grouped-fusion group key in
        ``parallel/sync.py``)."""
        qfields = ",".join(
            f"{name}:q{spec[0]}x{spec[1]}"
            for name, spec in sorted(self._sync_qspecs().items())
            if spec is not None
        )
        out: tuple = (f"sync_precision={qfields}",) if qfields else ()
        # class-axis placement changes the traced state SHAPES too, but the
        # marker still matters: it splits the persisted cache key and the
        # fusion group key for layouts that alias shapes (e.g. a (8, 8) dense
        # state vs an (8, 8) stack of a 64-class vector)
        csfields = ",".join(
            f"{name}:{lay.num_shards}x{lay.shard_size}"
            for name, lay in sorted(self.__dict__.get("_class_layouts", {}).items())
        )
        if csfields:
            out = out + (f"state_sharding={csfields}",)
        return out

    # ------------------------------------------------- class-axis placement
    def _class_layout(self, name: str) -> Optional[ClassShardLayout]:
        """The :class:`ClassShardLayout` of a class-sharded field, or None
        when ``name`` is replicated — the one test adopter update/compute
        bodies branch on (parallel/class_shard.py owns the actual math)."""
        return self.__dict__.get("_class_layouts", {}).get(name)

    def _touched_class_cells(self, state: Dict[str, Any], args: tuple) -> Optional[Dict[str, Any]]:
        """The flat element indices (into ``state[field].reshape(-1)``) the
        about-to-run update will touch, per state field — the cell-granular
        bookkeeping :meth:`_recovery_snapshot` feeds the
        :class:`~torchmetrics_tpu.parallel.class_shard.ClassShardMirror`.
        Metrics with sparse class-sharded updates override this (e.g. the
        multiclass confusion matrix: one ``target*C + pred`` cell per
        sample); the base returns None — full-snapshot recovery."""
        return None

    def _recovery_snapshot(self, state: Dict[str, Any], args: tuple) -> Any:
        """Executor recovery hook (ops/executor.py ``_take_recovery``): when
        this metric carries class-sharded state AND can name the cells the
        round touches, the incremental cell mirror replaces the whole-state
        host snapshot the donating dispatch would otherwise pay — for a 50k-
        class sharded confusion matrix that is ~16 KB of touched cells per
        round instead of ~10 GB of stacked state. Returns None (full-snapshot
        fallback) when cell bookkeeping is impossible. The mirror must cover
        EVERY state field or none: a field it cannot track would silently go
        stale in the restore source."""
        if not self.__dict__.get("_class_layouts"):
            return None
        cells = self._touched_class_cells(state, args)
        if cells is None or set(cells) != set(state):
            return None
        mirror = self.__dict__.get("_class_mirror")
        if mirror is None:
            from torchmetrics_tpu.parallel.class_shard import ClassShardMirror

            mirror = self.__dict__["_class_mirror"] = ClassShardMirror()
        return mirror.snapshot(state, cells, int(self._update_count))

    def _adopt_class_layouts(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Re-split incoming class-sharded fields into THIS metric's layout.

        A snapshot may carry a field dense (pre-sharding save, or saved by a
        replicated twin) or stacked for a different shard count (saved on
        d devices, restoring on d'). Both are pure metadata transforms —
        concatenate to dense, trim the padding, re-stack — exact for every
        eligible reduction, so ``load_state`` self-heals the layout before
        validation. Data-axis sharded stacks (``sharded=True`` restores) and
        unknown shapes pass through untouched for validate_state to judge.
        """
        layouts = self.__dict__.get("_class_layouts") or {}
        if not isinstance(state, dict):
            return state
        out = dict(state)
        # reverse direction: a snapshot saved by a class-sharded twin arriving
        # at a REPLICATED instance carries (S, shard_size, *rest) stacks —
        # gather them back to dense (reshape + trim the identity padding),
        # but only for fields whose reduction could legitimately have been
        # class-sharded elsewhere (same eligibility rule as add_state)
        for name, policy in (self.__dict__.get("_state_shardings") or {}).items():
            if policy != "replicated" or name in layouts:
                continue
            fx = self._reductions.get(name)
            if not isinstance(fx, str) or fx not in CLASS_SHARDABLE_REDUCTIONS:
                continue
            value = out.get(name)
            if value is None or isinstance(value, (list, tuple)) or not hasattr(value, "shape"):
                continue
            default = self._defaults.get(name)
            if isinstance(default, list) or not hasattr(default, "shape") or len(default.shape) < 1:
                continue
            num_classes, rest = int(default.shape[0]), tuple(default.shape[1:])
            shape = tuple(value.shape)
            # only the EXACT stacked geometry heals — any shard count d
            # yields (d, ceil(C/d), *rest), so shape[1] is determined by
            # shape[0]; anything else (e.g. a corrupt bogus leading dim,
            # which would be (2, C)) falls through to validate_state
            if (
                len(shape) == 2 + len(rest)
                and shape[2:] == rest
                and shape[0] >= 1
                and shape[1] == -(-num_classes // shape[0])
            ):
                out[name] = jnp.asarray(value).reshape((shape[0] * shape[1],) + rest)[:num_classes]
        if not layouts:
            return out
        for name, layout in layouts.items():
            value = out.get(name)
            if value is None or isinstance(value, (list, tuple)) or not hasattr(value, "shape"):
                continue
            rest = tuple(jnp.asarray(self._defaults[name]).shape[2:])
            shape = tuple(value.shape)
            if shape == (layout.num_shards, layout.shard_size) + rest:
                continue  # already this layout
            pad = identity_pad_value(self._reductions.get(name), jnp.asarray(value).dtype)
            if shape == (layout.num_classes,) + rest:
                # dense snapshot -> stack into our layout
                out[name] = _class_stack_dense(value, layout, pad_value=pad)
            elif (
                len(shape) == 2 + len(rest)
                and shape[2:] == rest
                and shape[0] >= 1
                and shape[1] == -(-layout.num_classes // shape[0])
            ):
                # stacked under a different shard count (the exact (d,
                # ceil(C/d)) geometry — see the reverse heal above):
                # gather + re-split
                arr = jnp.asarray(value)
                dense = arr.reshape((shape[0] * shape[1],) + rest)[: layout.num_classes]
                out[name] = _class_stack_dense(dense, layout, pad_value=pad)
        return out

    def _state_snapshot(self) -> Dict[str, Any]:
        """Shallow pre-call snapshot for transactional rollback: jnp arrays are
        immutable so references suffice; list states are list-copied. Unlike
        :meth:`_copy_state_dict` this does NOT mark the state escaped — the
        snapshot never outlives the call, so donation streaks survive."""
        return {k: (list(v) if isinstance(v, list) else v) for k, v in self._state.items()}

    def _rollback(
        self,
        state: Dict[str, Any],
        update_count: int,
        computed: Any,
        reduced: Optional[bool] = None,
        pending_shards: Any = "_keep",
    ) -> None:
        """Reinstall a pre-call snapshot after a failed update/forward.

        ``reduced``/``pending_shards`` restore the deferred-reduction flags
        captured alongside the snapshot, so a failed call on a sharded or
        locally-accumulated state cannot leave the flags claiming the opposite
        of what the restored arrays hold; omitted (the default) leaves them
        untouched for callers that never moved them."""
        obs.counter_inc("rollback.count")
        object.__setattr__(self, "_state", state)
        # the restored arrays may be aliased by whoever observed the failure
        self.__dict__["_state_escaped"] = True
        self.__dict__["_update_count"] = update_count
        self.__dict__["_computed"] = computed
        if reduced is not None:
            self.__dict__["_reduced"] = reduced
        if pending_shards != "_keep":
            self.__dict__["_pending_shards"] = pending_shards

    def _fold_pending(self) -> None:
        """Collapse an installed sharded state (``load_state(..., sharded=True)``)
        into the reduced layout — the on-demand re-reduce that keeps the OO
        surface (update/compute/sync) correct after a sharded restore."""
        shards = self.__dict__.get("_pending_shards")
        if shards is None:
            return
        t0 = time.perf_counter()
        with obs.span(obs.SPAN_REDUCE, owner=type(self).__name__, kind="fold_pending"):
            folded = fold_sharded_states(
                {k: jnp.asarray(self._state[k]) for k in self._defaults}, self._reductions
            )
        new_state = dict(self._state)
        new_state.update({k: jnp.asarray(v) for k, v in folded.items()})
        object.__setattr__(self, "_state", new_state)
        self.__dict__["_state_escaped"] = True
        self.__dict__["_pending_shards"] = None
        self.__dict__["_last_reduce_us"] = round((time.perf_counter() - t0) * 1e6, 1)

    def _mark_unreduced(self) -> None:
        """Record that state now holds locally-accumulated (unreduced) values;
        a no-op outside the deferred policy."""
        if self.__dict__.get("reduce_policy") == "deferred":
            self.__dict__["_reduced"] = False

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            # transactional contract (docs/ROBUSTNESS.md): any exception out of
            # this call leaves (_state, _update_count, _computed) exactly as
            # they were before it — no half-mutated accumulators. A sharded
            # restore folds first (re-reduce on demand) so the update operates
            # on reduced-layout arrays; the committed fold is itself a valid
            # pre-call state, so the rollback target is the folded snapshot.
            self._fold_pending()
            pre_count, pre_computed = self._update_count, self._computed
            pre_reduced = self.__dict__.get("_reduced", True)
            # count bumps BEFORE the cache clears: the async read pipeline's
            # compute-cache write-back double-checks the count around its
            # write (docs/ASYNC.md "Cache coherence"), and that check is only
            # race-free if an update's count moves first and its cache clear
            # lands second
            self._update_count += 1
            self._computed = None
            ex = self._get_executor()
            if ex is not None:
                handled = False
                try:
                    with obs.span(obs.SPAN_UPDATE, suffix=type(self).__name__):
                        handled = ex.run_update(args, kwargs)
                except BaseException:
                    # the executor restored _state itself (recovery reference);
                    # only the wrapper bookkeeping needs unwinding
                    self._update_count, self._computed = pre_count, pre_computed
                    self.__dict__["_reduced"] = pre_reduced
                    raise
                if handled:
                    self._mark_unreduced()
                    # post-commit: an observer raising here (e.g. a simulated
                    # preemption) must NOT unwind the committed update
                    self._notify_update()
                    return
            snapshot = self._state_snapshot()
            try:
                # per-metric profiler scope (SURVEY §5: the TPU analogue of the
                # reference's torch._C._log_api_usage_once telemetry); the body
                # routes through self._update_fn so the fault-injection harness
                # (testing/faults.py) can intercept every path uniformly
                with obs.span(obs.SPAN_UPDATE, suffix=type(self).__name__):
                    self._update_fn(*args, **kwargs)
            except TypeError as err:
                self._rollback(snapshot, pre_count, pre_computed, reduced=pre_reduced)
                if "got an unexpected keyword argument" in str(err) or "positional argument" in str(err):
                    raise TypeError(
                        f"Encountered an error while calling `update` of {type(self).__name__}: {err}"
                    ) from err
                raise
            except BaseException:
                self._rollback(snapshot, pre_count, pre_computed, reduced=pre_reduced)
                raise
            self._mark_unreduced()
            if self.compute_on_cpu:
                self._move_list_states_to_cpu()
            self._notify_update()

        return wrapped_func

    def _move_list_states_to_cpu(self) -> None:
        """Move list states to host memory (reference metric.py:489-494)."""
        cpu = jax.devices("cpu")[0] if any(d.platform == "cpu" for d in jax.devices()) else None
        for key, value in self._state.items():
            if isinstance(value, list) and cpu is not None:
                self._state[key] = [jax.device_put(v, cpu) for v in value]

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if self._update_count == 0:
                rank_zero_warn(
                    f"The ``compute`` method of metric {type(self).__name__}"
                    " was called before the ``update`` method which may lead to errors,"
                    " as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                return self._computed
            self._fold_pending()  # sharded restore: re-reduce before sync/compute
            auditor = self.__dict__.get("_integrity_auditor")
            if auditor is not None:
                # read-point integrity audit (integrity.py): verify the bits
                # before serving them — a divergence raises, restores the
                # verified baseline in place, or hands back the last-good
                # value to serve as a DegradedValue per on_divergence
                served = auditor.verify_read()
                if served is not None:
                    return served
            self.__dict__.pop("_serve_last_good", None)
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ), obs.span(obs.SPAN_COMPUTE, suffix=type(self).__name__):
                if self.__dict__.pop("_serve_last_good", False):
                    # the sync just degraded under on_sync_failure="last_good":
                    # serve the cached value + staleness instead of computing
                    # a silently-partial local result (never cached as
                    # _computed — it is stale by definition)
                    from torchmetrics_tpu.quarantine import DegradedValue

                    count, cached = self.__dict__["_last_good_compute"]
                    obs.histogram_observe(
                        "reads.staleness_age_updates", int(self._update_count) - count
                    )
                    return DegradedValue(
                        value=cached,
                        updates_behind=int(self._update_count) - count,
                        age_updates=count,
                    )
                # routed through self._compute_fn (not the closed-over bound
                # method) so the fault harness can intercept compute too
                value = _squeeze_if_scalar(self._compute_fn(*args, **kwargs))
            if self.compute_with_cache:
                self._computed = value
            if self.__dict__.get("_last_sync_ok", True):
                # the last-good cache behind on_sync_failure="last_good": only
                # values whose sync (if any) succeeded qualify
                self.__dict__["_last_good_compute"] = (int(self._update_count), value)
            return value

        return wrapped_func

    def update(self, *_: Any, **__: Any) -> None:  # overridden by subclass; rebound in __init__
        raise NotImplementedError

    def compute(self) -> Any:  # overridden by subclass; rebound in __init__
        raise NotImplementedError

    # ------------------------------------------------------ update observers
    def add_update_observer(self, callback: Callable[["Metric"], None]) -> Callable[[], None]:
        """Register ``callback(metric)`` to fire after every COMMITTED
        top-level ``update``/``forward`` — the autosave trigger point
        (io/checkpoint.py). Mid-``forward`` internal updates (where the live
        state transiently holds batch-only values) never notify, so an
        observer always sees a consistent accumulated state. Returns a
        zero-argument detach function."""
        observers = self.__dict__.setdefault("_update_observers", [])
        observers.append(callback)

        def detach() -> None:
            obs = self.__dict__.get("_update_observers")
            if obs is not None and callback in obs:
                obs.remove(callback)

        return detach

    def attach_integrity(
        self,
        every_n_updates: int = 1,
        on_divergence: str = "raise",
        snapshots: bool = True,
    ) -> Any:
        """Attach a bit-exact state-integrity auditor (integrity.py) riding
        the committed-update observer seam: every ``every_n_updates``-th
        commit captures the state's fingerprints (host readback on the read
        pipeline — the step loop never blocks), and every read verifies the
        live bits against them before serving. ``on_divergence`` picks the
        policy (``"raise"``/``"degraded"``/``"restore"`` — the
        ``on_shard_loss`` triple); ``snapshots=False`` keeps fingerprints
        only (no host copy, so ``"restore"`` degrades to ``"raise"``).
        Returns the attached :class:`~torchmetrics_tpu.integrity.IntegrityAuditor`
        (``auditor.detach()`` to remove; also exposed as
        ``metric.integrity``)."""
        from torchmetrics_tpu.integrity import IntegrityAuditor

        existing = self.__dict__.get("_integrity_auditor")
        if existing is not None:
            existing.detach()
        return IntegrityAuditor(
            self,
            every_n_updates=every_n_updates,
            on_divergence=on_divergence,
            snapshots=snapshots,
        ).attach()

    @property
    def integrity(self) -> Any:
        """The attached :class:`~torchmetrics_tpu.integrity.IntegrityAuditor`
        (None when :meth:`attach_integrity` was never called)."""
        return self.__dict__.get("_integrity_auditor")

    def _notify_update(self) -> None:
        """Fire update observers — only at top level (not inside forward's
        internal update pair, whose intermediate states are not checkpoints)."""
        if self.__dict__.get("_forward_depth", 0):
            return
        observers = self.__dict__.get("_update_observers")
        if observers:
            for callback in tuple(observers):
                callback(self)

    # ----------------------------------------------------------- forward paths
    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate into global state AND return the batch value (metric.py:281-312).

        When the executor is enabled, the whole forward — batch-state update,
        batch-value compute, and the global-state merge — runs as ONE compiled
        computation with the accumulated state donated (ops/executor.py)."""
        # the internal update pair must not fire update observers (their
        # intermediate states are batch-only, not valid checkpoints); the
        # single post-commit notification below covers the whole forward
        self.__dict__["_forward_depth"] = self.__dict__.get("_forward_depth", 0) + 1
        try:
            batch_val = self._forward_impl(*args, **kwargs)
        finally:
            self.__dict__["_forward_depth"] -= 1
        self._notify_update()
        return batch_val

    def _forward_impl(self, *args: Any, **kwargs: Any) -> Any:
        self._fold_pending()  # sharded restore: re-reduce before merging batches
        ex = self._get_executor()
        if ex is not None:
            handled, batch_val = ex.run_forward(args, kwargs)
            if handled:
                self._mark_unreduced()
                return batch_val
        if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
            return self._forward_full_state_update(*args, **kwargs)
        return self._forward_reduce_state_update(*args, **kwargs)

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """2× update strategy (reference metric.py:314-357), transactional.

        Any exception — first update, the batch-value update, or compute —
        restores the pre-call accumulated state; previously a raise after the
        mid-call ``reset`` lost the cached global state for good (ISSUE 2).
        The snapshot uses :meth:`_copy_state_dict` (marks the state escaped)
        because the inner ``update`` calls may route through the donating
        executor, which must not consume the arrays the snapshot references.
        """
        pre_state = self._copy_state_dict()
        pre_count, pre_computed = self._update_count, self._computed
        pre_reduced = self.__dict__.get("_reduced", True)
        try:
            self.update(*args, **kwargs)
            _update_count = self._update_count
            self._to_sync = self.dist_sync_on_step
            cache = self._copy_state_dict()
            self._computed = None
            self.reset()
            self.update(*args, **kwargs)
            batch_val = self.compute()
            # restore context
            self._update_count = _update_count
            self._state = cache
            self._mark_unreduced()  # the restored cache holds local accumulation
        except BaseException:
            self._rollback(pre_state, pre_count, pre_computed, reduced=pre_reduced)
            raise
        finally:
            self._to_sync = self.sync_on_compute
            self._should_unsync = True
        self._computed = None
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """1× update + state-merge strategy (reference metric.py:359-397),
        transactional: a raise from the batch update, the batch compute, or
        the merge restores the pre-call global state and count."""
        global_state = self._copy_state_dict()
        _update_count = self._update_count
        pre_computed = self._computed
        pre_reduced = self.__dict__.get("_reduced", True)
        self.reset()
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        try:
            self.update(*args, **kwargs)
            batch_val = self.compute()

            self._update_count = _update_count + 1
            self._reduce_states(global_state)
            self._mark_unreduced()  # merged state holds local accumulation again
        except BaseException:
            self._rollback(
                {k: (list(v) if isinstance(v, list) else v) for k, v in global_state.items()},
                _update_count,
                pre_computed,
                reduced=pre_reduced,
            )
            raise
        finally:
            self._to_sync = self.sync_on_compute
            self._should_unsync = True
        self._computed = None
        return batch_val

    def _reduce_states(self, incoming_state: Dict[str, Any]) -> None:
        """Merge incoming (global) state into current (batch) state (metric.py:399-431)."""
        for attr in self._defaults:
            local_state = self._state[attr]
            global_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == "sum":
                reduced = global_state + local_state
            elif reduce_fn == "mean":
                reduced = ((self._update_count - 1) * global_state + local_state) / self._update_count
            elif reduce_fn == "max":
                reduced = jnp.maximum(global_state, local_state)
            elif reduce_fn == "min":
                reduced = jnp.minimum(global_state, local_state)
            elif reduce_fn == "cat":
                if isinstance(global_state, list) or isinstance(local_state, list):
                    reduced = list(global_state) + list(local_state)
                else:
                    reduced = jnp.concatenate([jnp.atleast_1d(global_state), jnp.atleast_1d(local_state)])
            elif reduce_fn is None and isinstance(global_state, jnp.ndarray):
                reduced = jnp.stack([global_state, local_state])
            elif reduce_fn is None and isinstance(global_state, list):
                reduced = _flatten([global_state, local_state])
            elif callable(reduce_fn):
                reduced = reduce_fn(jnp.stack([jnp.asarray(global_state), jnp.asarray(local_state)]))
            else:
                reduced = global_state
            self._state[attr] = reduced

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    # ------------------------------------------------------------------- sync
    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        axis_name: Optional[Union[str, Sequence[str]]] = None,
    ) -> None:
        """All-reduce states across devices/hosts per declared reductions.

        Reference metric.py:496-538, rebuilt for the mesh: inside a traced context
        that binds ``axis_name`` (pmap/shard_map), each state syncs with a single
        lax collective. On a multi-process (multi-host) runtime outside jit, a DCN
        process_allgather + local reduce runs instead. Single-process outside a
        trace, sync is a no-op (states are already global).
        """
        if self._is_synced and should_sync:
            raise TorchMetricsUserError("The Metric has already been synced.")
        self._fold_pending()  # sharded restore: collapse shards before collectives
        axis_name = axis_name if axis_name is not None else self.sync_axis
        # str or sequence of axis names (multi-axis data×sequence sync)
        in_trace = axis_name is not None and in_named_axis_context(axis_name)
        distributed_available = distributed_available or self.distributed_available_fn
        if not should_sync or (not in_trace and not distributed_available()):
            return
        # cache prior to syncing (restored by unsync); containment: a failed
        # sync must leave no half-synced state behind — the synced dict is
        # built fully before installation, and the cache is cleared on failure
        # so a later sync/unsync cycle starts clean
        self._cache = self._copy_state_dict()
        t0 = time.perf_counter()
        try:
            with obs.span(obs.SPAN_REDUCE, owner=type(self).__name__, kind="sync"):
                dist_sync_fn = dist_sync_fn or self.dist_sync_fn
                if dist_sync_fn is not None:
                    self._state = {k: dist_sync_fn(v, self._reductions.get(k), axis_name) for k, v in self._state.items()}
                elif in_trace:
                    self._state = sync_states(
                        self._state, self._reductions, axis_name, qspecs=self._sync_qspecs()
                    )
                else:  # multi-host, outside jit: bounded with a degradation policy
                    self._host_sync_bounded()
        except BaseException:
            self._cache = None
            raise
        self._is_synced = True
        # state now holds globally-reduced values; unsync restores the flag
        # along with the local state
        self.__dict__["_reduced_pre_sync"] = self.__dict__.get("_reduced", True)
        self.__dict__["_reduced"] = True
        if not in_trace:  # tracer timings are meaningless; record host syncs only
            self.__dict__["_last_reduce_us"] = round((time.perf_counter() - t0) * 1e6, 1)

    def _host_sync_bounded(self) -> None:
        """The ``process_allgather`` path under ``sync_timeout`` /
        ``on_sync_failure`` (ISSUE 2 tentpole #3): ``"raise"`` propagates with
        local state intact; ``"local"`` keeps serving local-only values with a
        rank-zero warning, observable via :attr:`last_sync_ok`; ``"retry"``
        re-attempts the whole gather with capped exponential backoff
        (io/retry.py) before propagating — the transient-abort case (a peer
        restarting mid-rendezvous) recovers without losing the epoch."""

        def gather_all() -> Dict[str, Any]:
            return {
                k: host_sync_value(v, self._reductions.get(k), timeout=self.sync_timeout)
                for k, v in self._state.items()
            }

        try:
            if self.on_sync_failure == "retry":
                from torchmetrics_tpu.io.retry import RetryPolicy, call_with_retries, default_sync_retries

                retries = self.sync_retries if self.sync_retries is not None else default_sync_retries()
                synced = call_with_retries(
                    gather_all,
                    RetryPolicy(max_retries=retries),
                    what=f"multi-host sync of {type(self).__name__}",
                )
            else:
                synced = gather_all()
        except Exception as err:
            if self.on_sync_failure not in ("local", "last_good"):
                raise
            self.__dict__["_last_sync_ok"] = False
            if self.on_sync_failure == "last_good" and self.__dict__.get("_last_good_compute") is not None:
                # degraded read (docs/LANES.md "Failure semantics"): serve the
                # last successfully-synced value with staleness metadata
                # instead of a silently-partial local one
                self.__dict__["_serve_last_good"] = True
                obs.counter_inc("sync.degraded_last_good")
                obs.fault_breadcrumb(
                    "sync_degraded_last_good",
                    domain="sync",
                    data={"metric": type(self).__name__, "error": f"{type(err).__name__}: {err}"},
                )
                rank_zero_warn(
                    f"Multi-host sync of {type(self).__name__} failed ({type(err).__name__}: {err});"
                    " serving the last-good value per on_sync_failure='last_good'"
                    " (staleness metadata attached).",
                    TorchMetricsUserWarning,
                )
                return
            obs.counter_inc("sync.degraded_local")
            obs.fault_breadcrumb(
                "sync_degraded_local",
                domain="sync",
                data={"metric": type(self).__name__, "error": f"{type(err).__name__}: {err}"},
            )
            rank_zero_warn(
                f"Multi-host sync of {type(self).__name__} failed ({type(err).__name__}: {err});"
                f" degrading to local-only state per on_sync_failure={self.on_sync_failure!r}."
                " Values computed this step cover THIS process's data only.",
                TorchMetricsUserWarning,
            )
            return
        self._state = synced
        self.__dict__["_last_sync_ok"] = True

    @property
    def last_sync_ok(self) -> bool:
        """False when the most recent multi-host sync degraded to local-only
        state (``on_sync_failure="local"``); True after any successful sync."""
        return self.__dict__.get("_last_sync_ok", True)

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore pre-sync local state (reference metric.py:540-560)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise TorchMetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise TorchMetricsUserError("The internal cache should exist to unsync the Metric.")
        self._state = self._cache
        self._cache = None
        self._is_synced = False
        # local (pre-sync) state is back: its reduction is pending again
        self.__dict__["_reduced"] = self.__dict__.pop("_reduced_pre_sync", True)

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
        axis_name: Optional[Union[str, Sequence[str]]] = None,
    ) -> Generator[None, None, None]:
        """Sync on entry, restore on exit (reference metric.py:562-597).

        The unsync runs in a ``finally`` so an exception inside the body (a
        failing ``compute``) cannot strand the metric in the synced state —
        part of the transaction guarantee (docs/ROBUSTNESS.md)."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            should_sync=should_sync,
            distributed_available=distributed_available,
            axis_name=axis_name,
        )
        try:
            yield
        finally:
            self.unsync(should_unsync=self._is_synced and should_unsync)

    # ----------------------------------------------------- asynchronous reads
    #
    # compute_async()/sync_async() (docs/ASYNC.md): the blocking tail of a
    # read — waiting on the fused reduce, the bounded multi-host gather, the
    # host finalize and D2H — runs on the read pipeline's worker thread
    # (ops/async_read.py) against a by-reference snapshot of the live state.
    # The snapshot marks the state escaped, so the executor's next donating
    # dispatch copies before it donates (the same seam the recovery snapshot
    # uses): the step loop's next update() writes a fresh buffer while the
    # in-flight read drains the old one. Worker-side evaluation runs on a
    # cached detached clone because functional_compute/compute swap the live
    # _state during the call — tracing or computing on the live object off
    # the main thread races every concurrent update.

    def _read_clone(self) -> "Metric":
        """The detached clone the pipeline worker computes on (cached; rebuilt
        when the declared state layout changes — a laned capacity respec, a
        ``set_dtype``). Only its CODE and declared metadata matter: every read
        installs a fresh state snapshot before running."""
        sig = tuple(
            (k, "list") if isinstance(v, list) else (k, str(v.dtype), tuple(int(d) for d in v.shape))
            for k, v in self._defaults.items()
        )
        cached = self.__dict__.get("_read_clone_cache")
        if cached is not None and cached[0] == sig:
            return cached[1]
        clone = copy.deepcopy(self)
        # reads never dispatch through an executor; a clone must never own one
        clone.__dict__["_executor_enabled"] = False
        self.__dict__["_read_clone_cache"] = (sig, clone)
        return clone

    def _async_inline_reason(self) -> Optional[str]:
        """Why this metric's reads must resolve inline (None = fully async).

        A metric holding CHILD metric objects (wrappers, compositional
        metrics) keeps state outside ``_state``, so a snapshot-and-clone read
        would serve the children's state as of clone creation — stale. Those
        metrics evaluate on the calling thread instead (the future resolves
        through the pipeline, but the compute cost lands inline; documented
        in docs/ASYNC.md "Inline fallbacks")."""
        cached = self.__dict__.get("_async_inline_reason_c", "?")
        if cached != "?":
            return cached
        reason = None
        for k, v in self.__dict__.items():
            if k in ("_state", "_defaults", "_read_clone_cache"):
                continue
            if isinstance(v, Metric):
                reason = f"holds child metric under attribute {k!r}"
                break
            if isinstance(v, (list, tuple)) and any(isinstance(el, Metric) for el in v):
                reason = f"holds child metrics under attribute {k!r}"
                break
            if isinstance(v, dict) and any(isinstance(el, Metric) for el in v.values()):
                reason = f"holds child metrics under attribute {k!r}"
                break
        self.__dict__["_async_inline_reason_c"] = reason
        return reason

    def _capture_read_flags(self) -> Dict[str, Any]:
        """Submission-time bookkeeping a read job needs: the committed count,
        the deferred-reduction flags, the last-good cache and sync intent —
        captured here so caller-side mutations after submission cannot bleed
        into an in-flight read (and vice versa)."""
        d = self.__dict__
        return {
            "count": int(d.get("_update_count", 0)),
            "reduced": d.get("_reduced", True),
            "pending_shards": d.get("_pending_shards"),
            "last_good": d.get("_last_good_compute"),
            "to_sync": d.get("_to_sync", True),
            "cache": bool(d.get("compute_with_cache", True)),
        }

    def compute_async(self) -> Any:
        """Non-blocking :meth:`compute`: returns a
        :class:`~torchmetrics_tpu.ops.async_read.MetricFuture` resolving to
        exactly what a blocking ``compute()`` would return for the state as
        of THIS call — same value bit-for-bit, same ``on_sync_failure``
        policies, same :class:`~torchmetrics_tpu.quarantine.DegradedValue`
        degraded serving, same errors (re-raised by ``future.result()``).

        The caller never blocks: the fused reduce is *dispatched* here (JAX
        async dispatch enqueues device work without waiting) and everything
        that must wait — device completion, the bounded multi-host gather,
        D2H — runs on the read pipeline's worker. The live state is
        double-buffered by construction: this call marks it escaped, so the
        next ``update()``'s donating dispatch copies first, and the step
        loop proceeds immediately while the read drains. Mutating the metric
        (update/reset/load_state) before the future resolves is safe — the
        future still serves the submission-time value, and the live
        ``_reduced``/``deferred_pending`` flags are never touched by the
        in-flight read. See docs/ASYNC.md for the staleness and cache
        contract."""
        from torchmetrics_tpu.ops import async_read as _async

        owner = type(self).__name__
        with obs.span(obs.SPAN_COMPUTE_ASYNC, suffix=owner):
            body = self._prepare_async_read()
            return _async.get_pipeline().submit(
                body, owner=owner, submitted_count=int(self._update_count)
            )

    def _prepare_async_read(self) -> Callable[[], Any]:
        """The caller-side half of one asynchronous compute: dispatch what can
        be dispatched, snapshot what must stay consistent, and return the
        worker-side body. Collections compose member bodies into one job
        through this seam (and :class:`~torchmetrics_tpu.lanes.LanedMetric`
        overrides it with the lane-aware read body)."""
        cached = self._computed
        if cached is not None:
            return lambda: _async_materialize(cached)
        reason = self._async_inline_reason()
        if reason is not None:
            obs.counter_inc("reads.inline_compute")
            value = self.compute()  # inline fallback: blocking semantics on the caller
            return lambda: _async_materialize(value)
        self._fold_pending()  # device dispatch only: enqueued, not awaited
        snapshot = self._copy_state_dict()  # by-reference; marks state escaped
        flags = self._capture_read_flags()
        clone = self._read_clone()
        body = lambda: self._async_compute_job(clone, snapshot, flags)  # noqa: E731
        auditor = self.__dict__.get("_integrity_auditor")
        if auditor is not None:
            # verify the submission-time snapshot ON THE WORKER before the
            # read resolves (integrity.py): the future carries the same
            # policy outcomes a blocking read would, without blocking here
            body = auditor.wrap_async_read(body, snapshot, flags)
        return body

    def _install_read_snapshot(self, clone: "Metric", snapshot: Dict[str, Any], flags: Dict[str, Any]) -> None:
        """WORKER-SIDE: stage a submission-time snapshot into the read clone
        so the clone's ``compute``/``sync`` replays blocking semantics against
        it (single worker thread -> the shared clone is used serially)."""
        object.__setattr__(clone, "_state", dict(snapshot))
        d = clone.__dict__
        d["_state_escaped"] = True
        d["_update_count"] = flags["count"]
        d["_computed"] = None
        d["_reduced"] = flags["reduced"]
        d["_pending_shards"] = flags["pending_shards"]
        d["_is_synced"] = False
        d["_cache"] = None
        d["_last_sync_ok"] = True
        d["_last_good_compute"] = flags["last_good"]
        d.pop("_serve_last_good", None)
        d["_to_sync"] = flags["to_sync"]
        d["_should_unsync"] = True

    def _async_compute_job(self, clone: "Metric", snapshot: Dict[str, Any], flags: Dict[str, Any]) -> Any:
        """WORKER-SIDE: the pipelined read body — reduce/sync per policy,
        host finalize, materialize, then the guarded cache write-back."""
        self._install_read_snapshot(clone, snapshot, flags)
        value = _async_materialize(clone.compute())
        self._writeback_read_result(clone, flags, value)
        return value

    def _writeback_read_result(self, clone: "Metric", flags: Dict[str, Any], value: Any) -> None:
        """WORKER-SIDE cache coherence (docs/ASYNC.md): a resolved read may
        refresh the live compute cache and last-good/sync bookkeeping ONLY
        while the live metric still sits at the submission-time update count.
        The count-bump-then-cache-clear ordering in ``_wrap_update`` plus the
        re-check after the write make a concurrent update always win: either
        this write never happens, or the update's cache clear lands after it,
        or the re-check undoes it."""
        from torchmetrics_tpu.quarantine import DegradedValue

        if self.__dict__.get("_update_count") != flags["count"]:
            return
        self.__dict__["_last_sync_ok"] = clone.__dict__.get("_last_sync_ok", True)
        last_good = clone.__dict__.get("_last_good_compute")
        if last_good is not None:
            self.__dict__["_last_good_compute"] = last_good
        if flags["cache"] and not isinstance(value, DegradedValue) and self.__dict__.get("_computed") is None:
            self.__dict__["_computed"] = value
            if self.__dict__.get("_update_count") != flags["count"]:
                self.__dict__["_computed"] = None  # an update landed mid-write: drop the stale cache

    def sync_async(self, axis_name: Optional[Union[str, Sequence[str]]] = None) -> Any:
        """Non-blocking read-side :meth:`sync`: returns a
        :class:`~torchmetrics_tpu.ops.async_read.MetricFuture` resolving to
        the SYNCED state pytree (the dict :meth:`state` would export after a
        blocking ``sync()``, every array ready) for the state as of this
        call. Unlike blocking ``sync()``, the live metric is never mutated —
        this is a read, so there is nothing to ``unsync`` and no
        ``_is_synced`` latch to manage from another thread. Honors
        ``sync_timeout`` and every ``on_sync_failure`` policy; failures
        surface through ``future.result()`` exactly as ``sync()`` would
        raise them."""
        from torchmetrics_tpu.ops import async_read as _async

        owner = type(self).__name__
        with obs.span(obs.SPAN_COMPUTE_ASYNC, suffix=owner, kind="sync"):
            body = self._prepare_async_sync(axis_name)
            return _async.get_pipeline().submit(
                body, owner=owner, submitted_count=int(self._update_count)
            )

    def _prepare_async_sync(self, axis_name: Any = None) -> Callable[[], Any]:
        """Caller-side half of one asynchronous sync (see
        :meth:`_prepare_async_read`)."""
        self._fold_pending()
        reason = self._async_inline_reason()
        if reason is not None:
            obs.counter_inc("reads.inline_compute")
            with self.sync_context(should_sync=True, should_unsync=True, axis_name=axis_name):
                out = self.state()  # inline fallback: blocking semantics on the caller
            return lambda: _async_materialize(out)
        snapshot = self._copy_state_dict()
        flags = self._capture_read_flags()
        clone = self._read_clone()
        return lambda: self._async_sync_job(clone, snapshot, flags, axis_name)

    def _async_sync_job(
        self, clone: "Metric", snapshot: Dict[str, Any], flags: Dict[str, Any], axis_name: Any
    ) -> Dict[str, Any]:
        """WORKER-SIDE: bounded sync on the snapshot via the clone, then the
        materialized state export."""
        self._install_read_snapshot(clone, snapshot, flags)
        clone.sync(should_sync=True, axis_name=axis_name)
        out = _async_materialize(clone.state())
        if self.__dict__.get("_update_count") == flags["count"]:
            self.__dict__["_last_sync_ok"] = clone.__dict__.get("_last_sync_ok", True)
        return out

    # ------------------------------------------------------- pure / functional
    def _copy_state_dict(self) -> Dict[str, Any]:
        """Shallow-copy live state; jnp arrays are immutable so no deepcopy needed."""
        self.__dict__["_state_escaped"] = True  # handing out aliases: no donation until re-owned
        out: Dict[str, Any] = {}
        for k, v in self._state.items():
            out[k] = list(v) if isinstance(v, list) else v
        return out

    #: reserved state key carrying the update count through state()/load_state
    _STATE_COUNT_KEY = "_update_count"

    #: reserved state key marking a sharded export (value = shard count); set by
    #: state() while a sharded restore is pending so the export round-trips
    #: through load_state without the caller re-passing ``sharded=True``
    _STATE_SHARDS_KEY = "_sharded_shards"

    def state(self) -> Dict[str, Any]:
        """The live state as a pytree (entry point of the pure API).

        The export carries the update count under the reserved key
        ``"_update_count"`` (a plain int leaf) so :meth:`load_state`
        round-trips it without the caller passing it explicitly; the
        functional entry points strip the key on input, and
        :meth:`merge_states` drops it (it iterates declared states only).
        While a sharded restore is pending (``load_state(..., sharded=True)``
        with no fold yet), the export also carries the shard count under
        ``"_sharded_shards"`` so the stacked layout round-trips losslessly.
        """
        out = self._copy_state_dict()
        out[self._STATE_COUNT_KEY] = int(self._update_count)
        shards = self.__dict__.get("_pending_shards")
        if shards is not None:
            out[self._STATE_SHARDS_KEY] = int(shards)
        return out

    #: reserved (non-state) keys a state() export may carry
    _RESERVED_STATE_KEYS = (_STATE_COUNT_KEY, _STATE_SHARDS_KEY)

    #: reductions under which a state's array shape is invariant across
    #: updates/merges/syncs — the only fields whose shape `validate="strict"`
    #: can check exactly ("cat" grows, None stacks, callables are opaque)
    _SHAPE_INVARIANT_REDUCTIONS = ("sum", "mean", "max", "min")

    def state_spec(self) -> Dict[str, Any]:
        """Declared layout of this metric's state pytree, exported alongside
        :meth:`state` so checkpointing layers can persist and later verify a
        restore target (ISSUE 2 tentpole #2).

        Returns a plain-Python (JSON-serialisable) dict::

            {"spec_version": 1, "class": <type name>,
             "count_key": "_update_count",
             "fields": {name: {"kind": "array"|"list", "shape": tuple|None,
                               "dtype": str|None, "reduction": str|None,
                               "shape_invariant": bool}}}

        ``shape`` / ``dtype`` describe the default (fresh) state;
        ``shape_invariant`` tells a validator whether the live shape must
        still equal it ("sum"/"mean"/"max"/"min" accumulators) or may have
        legitimately grown ("cat" concatenations, ``None`` stacks, custom
        reductions).
        """
        fields: Dict[str, Any] = {}
        for name, default in self._defaults.items():
            fx = self._reductions.get(name)
            reduction = fx if isinstance(fx, str) else ("custom" if callable(fx) else None)
            if isinstance(default, list):
                fields[name] = {
                    "kind": "list", "shape": None, "dtype": None,
                    "reduction": reduction, "shape_invariant": False,
                }
            else:
                arr = jnp.asarray(default)
                fields[name] = {
                    "kind": "array",
                    "shape": tuple(int(d) for d in arr.shape),
                    "dtype": str(arr.dtype),
                    "reduction": reduction,
                    "shape_invariant": fx in self._SHAPE_INVARIANT_REDUCTIONS,
                }
                layout = self.__dict__.get("_class_layouts", {}).get(name)
                if layout is not None:
                    # class-sharded fields record their layout so a restore
                    # target can tell "(8, 8) stack of 64 classes" from a
                    # plain (8, 8) dense state (keys absent when replicated,
                    # keeping replicated specs byte-identical to pre-sharding)
                    fields[name]["state_sharding"] = "class_axis"
                    fields[name]["num_classes"] = int(layout.num_classes)
                    fields[name]["class_shards"] = int(layout.num_shards)
        return {
            "spec_version": 1,
            "class": type(self).__name__,
            "count_key": self._STATE_COUNT_KEY,
            "fields": fields,
        }

    def validate_state(
        self,
        state: Dict[str, Any],
        mode: str = "strict",
        check_finite: bool = False,
        sharded: bool = False,
    ) -> Dict[str, Any]:
        """Check a state pytree against this metric's :meth:`state_spec`.

        Returns the (possibly cast) state dict; raises
        :class:`~torchmetrics_tpu.utils.exceptions.StateCorruptionError` on any
        mismatch. Modes:

        - ``"strict"``: tree structure (every declared field present, right
          kind), exact dtype, and exact shape for shape-invariant fields.
          Metadata-only — zero device dispatches.
        - ``"cast"``: like strict, but a dtype mismatch casts to the declared
          dtype instead of raising (shape/structure problems still raise).
        - ``"off"``: no checks, state returned untouched.

        ``check_finite=True`` additionally scans floating-point array fields
        for NaN/Inf (one device reduction per float field) — the corrupted
        checkpoint that parses fine but poisons every later merge.

        ``sharded=True`` validates the stacked per-device layout instead
        (docs/SHARDING.md): every array field carries a leading shard axis, so
        shape-invariant fields must match ``(N, *declared_shape)`` with the
        SAME ``N`` across all fields.
        """
        if mode == "off":
            # check_finite is an explicit request and must still run — it used
            # to be silently skipped here, letting a NaN-poisoned checkpoint
            # through whenever structural validation was disabled
            if check_finite:
                for name, value in state.items():
                    if name in self._RESERVED_STATE_KEYS:
                        continue
                    if isinstance(value, (list, tuple)):
                        for i, el in enumerate(value):
                            self._check_field_finite(name, el, index=i)
                    else:
                        self._check_field_finite(name, value, per_shard=sharded)
            return state
        if mode not in ("strict", "cast"):
            raise ValueError(f"validate must be 'strict', 'cast' or 'off', got {mode!r}")
        if not isinstance(state, dict):
            raise obs.flighted(StateCorruptionError(
                f"{type(self).__name__}: state must be a dict pytree, got {type(state).__name__}"
            ), domain="checkpoint")
        spec = self.state_spec()["fields"]
        out: Dict[str, Any] = dict(state)
        shard_counts: Dict[str, int] = {}
        for name, field_spec in spec.items():
            if name not in state:
                raise obs.flighted(StateCorruptionError(
                    f"{type(self).__name__}: state is missing declared field {name!r}"
                    f" (has {sorted(k for k in state if k not in self._RESERVED_STATE_KEYS)})"
                ), domain="checkpoint")
            value = state[name]
            if field_spec["kind"] == "list":
                if sharded:
                    raise obs.flighted(StateCorruptionError(
                        f"{type(self).__name__}: field {name!r} is a list state; list states"
                        " cannot carry a shard axis (sharded=True)"
                    ), domain="checkpoint")
                if not isinstance(value, (list, tuple)):
                    raise obs.flighted(StateCorruptionError(
                        f"{type(self).__name__}: field {name!r} is a list state but the restored"
                        f" value is {type(value).__name__}"
                    ), domain="checkpoint")
                if check_finite:
                    for i, el in enumerate(value):
                        self._check_field_finite(name, el, index=i)
                continue
            if isinstance(value, (list, tuple)):
                raise obs.flighted(StateCorruptionError(
                    f"{type(self).__name__}: field {name!r} is an array state but the restored"
                    f" value is a {type(value).__name__}"
                ), domain="checkpoint")
            arr = value if hasattr(value, "shape") and hasattr(value, "dtype") else np.asarray(value)
            if sharded:
                if arr.ndim < 1 or (
                    field_spec["shape_invariant"] and tuple(arr.shape[1:]) != field_spec["shape"]
                ):
                    raise obs.flighted(StateCorruptionError(
                        f"{type(self).__name__}: sharded field {name!r} has shape {tuple(arr.shape)}"
                        f" but the stacked layout requires (num_shards, *{field_spec['shape']})"
                    ), domain="checkpoint")
                shard_counts[name] = int(arr.shape[0])
            elif field_spec["shape_invariant"] and tuple(arr.shape) != field_spec["shape"]:
                raise obs.flighted(StateCorruptionError(
                    f"{type(self).__name__}: field {name!r} has shape {tuple(arr.shape)} but this"
                    f" metric's state layout requires {field_spec['shape']}"
                ), domain="checkpoint")
            if str(arr.dtype) != field_spec["dtype"]:
                if mode == "cast":
                    out[name] = jnp.asarray(value).astype(field_spec["dtype"])
                else:
                    raise obs.flighted(StateCorruptionError(
                        f"{type(self).__name__}: field {name!r} has dtype {arr.dtype} but this"
                        f" metric's state layout requires {field_spec['dtype']}"
                        " (use validate='cast' to convert)"
                    ), domain="checkpoint")
            if check_finite:
                self._check_field_finite(name, out[name], per_shard=sharded)
        if sharded and len(set(shard_counts.values())) > 1:
            raise obs.flighted(StateCorruptionError(
                f"{type(self).__name__}: sharded fields disagree on the shard count: {shard_counts}"
            ), domain="checkpoint")
        return out

    def _check_field_finite(
        self, name: str, value: Any, index: Optional[int] = None, per_shard: bool = False
    ) -> None:
        arr = jnp.asarray(value)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            return
        if per_shard and arr.ndim >= 1:
            # stacked sharded (deferred) layout: scan every shard and NAME the
            # poisoned ones — a single per-device NaN would otherwise fold into
            # every reduced value at the next re-reduce
            shard_ok = jnp.all(jnp.isfinite(arr).reshape(arr.shape[0], -1), axis=1)
            if not bool(jnp.all(shard_ok)):
                bad = [int(i) for i in np.flatnonzero(~np.asarray(shard_ok))]
                raise obs.flighted(StateCorruptionError(
                    f"{type(self).__name__}: sharded field {name!r} contains non-finite values"
                    f" in shard(s) {bad} (check_finite=True rejects NaN/Inf accumulators)"
                ), domain="checkpoint")
            return
        if not bool(jnp.all(jnp.isfinite(arr))):
            where = f"{name!r}[{index}]" if index is not None else f"{name!r}"
            raise obs.flighted(StateCorruptionError(
                f"{type(self).__name__}: field {where} contains non-finite values"
                " (check_finite=True rejects NaN/Inf accumulators)"
            ), domain="checkpoint")

    def init_state(self) -> Dict[str, Any]:
        """A fresh default state pytree (the pure analogue of ``reset``)."""
        return {k: (list(v) if isinstance(v, list) else jnp.asarray(v)) for k, v in copy.deepcopy(self._defaults).items()}

    def functional_init(self) -> Dict[str, Any]:
        """Alias of :meth:`init_state` — the uniform functional-protocol name
        shared with ``MetricCollection`` and the wrapper family."""
        return self.init_state()

    # ------------------------------------------------- sharded (deferred) API
    def init_sharded_state(self, num_shards: int) -> Dict[str, Any]:
        """A fresh state pytree in the sharded layout: every field gains a
        leading shard axis of size ``num_shards`` (docs/SHARDING.md). Feed it
        through ``shard_map`` with :meth:`sharded_state_spec` as the state
        in/out spec and accumulate locally with :meth:`functional_update`
        (unshard/reshard around the call, or use the executor's
        ``make_deferred_collection_step`` which does it for you)."""
        if any(isinstance(v, list) for v in self._defaults.values()):
            raise TorchMetricsUserError(
                f"{type(self).__name__} holds list states, which cannot carry a shard axis;"
                " deferred sharded accumulation needs fixed-shape states"
            )
        return init_sharded_states(self.init_state(), num_shards)

    def sharded_state_spec(self, axis_name: Optional[str] = None) -> Dict[str, Any]:
        """PartitionSpec pytree partitioning every state field's leading shard
        axis along ``axis_name`` (default :attr:`sync_axis`) — the
        ``shard_map`` in/out spec of the local-accumulation step."""
        axis = axis_name or self.sync_axis
        return local_accumulate_spec(self.init_state(), axis)

    def reduce_sharded_state(
        self, state: Dict[str, Any], axis_name: Optional[Union[str, Sequence[str]]] = None
    ) -> Dict[str, Any]:
        """The deferred-reduction read point for this metric, inside a
        ``shard_map`` body: drop the local shard axis and apply every declared
        ``dist_reduce_fx`` exactly once (one fused rendezvous for all
        sum-family fields via ``sync_states``). Honors ``dist_sync_fn`` and
        the reserved count key like :meth:`functional_sync`."""
        from torchmetrics_tpu.parallel.sync import unshard_local_state

        with obs.device_span(obs.SPAN_REDUCE):
            return self.functional_sync(unshard_local_state(state), axis_name)

    def reshard_state(self, state: Dict[str, Any], to_num_shards: int) -> Dict[str, Any]:
        """Re-split this metric's stacked sharded state from its current shard
        count onto ``to_num_shards`` — save on N devices, continue on M
        (docs/SHARDING.md "Resharding"). Routes through the ONE audited
        ``parallel/reshard.py`` seam: fold to the topology-neutral canonical
        form, then reinstall per each field's declared ``dist_reduce_fx``
        (exact for the sum/mean/max/min families; ``cat``/``None``/callable
        fields raise :class:`TopologyMismatchError` — carry those as a
        read-point baseline, see ``DeferredCollectionStep.restore_states``)."""
        from torchmetrics_tpu.parallel.reshard import ShardLayout, layout_of, reshard_states

        return reshard_states(
            state, layout_of(state), ShardLayout(int(to_num_shards)), self._reductions
        )

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure update: ``(state, batch) -> state'``. jit/vmap/shard_map-safe.

        Swaps the given state in, runs the (unwrapped) ``update`` body, captures
        the result and restores the live state — so the same subclass code serves
        both the eager OO shell and fully traced training steps.
        """
        saved = self._state
        try:
            object.__setattr__(
                self,
                "_state",
                {k: (list(v) if isinstance(v, list) else v) for k, v in state.items() if k not in self._RESERVED_STATE_KEYS},
            )
            with obs.span(obs.SPAN_UPDATE, suffix=type(self).__name__), obs.device_span(
                obs.SPAN_UPDATE, suffix=type(self).__name__
            ):
                self._update_fn(*args, **kwargs)
            return self._copy_state_dict()
        finally:
            object.__setattr__(self, "_state", saved)

    def functional_compute(self, state: Dict[str, Any]) -> Any:
        """Pure compute: ``state -> value``. jit-safe."""
        saved = self._state
        try:
            object.__setattr__(
                self,
                "_state",
                {k: (list(v) if isinstance(v, list) else v) for k, v in state.items() if k not in self._RESERVED_STATE_KEYS},
            )
            with obs.span(obs.SPAN_COMPUTE, suffix=type(self).__name__):
                return _squeeze_if_scalar(self._compute_fn())
        finally:
            object.__setattr__(self, "_state", saved)

    def functional_forward(
        self, state: Dict[str, Any], *args: Any, update_count: Optional[int] = None, **kwargs: Any
    ) -> tuple:
        """Pure forward: ``(state, batch) -> (state', batch_value)``.

        For metrics holding ``"mean"``-reduced states, pass ``update_count`` (the
        number of updates already merged into ``state``) so the running mean is
        count-weighted like the stateful path (reference metric.py:399-431);
        without it both sides weigh equally.
        """
        batch_state = self.functional_update(self.functional_init(), *args, **kwargs)
        batch_value = self.functional_compute(batch_state)
        counts = (update_count, 1) if update_count is not None else None
        return self.merge_states(state, batch_state, counts=counts), batch_value

    def functional_sync(self, state: Dict[str, Any], axis_name: Optional[Union[str, Sequence[str]]] = None) -> Dict[str, Any]:
        """Pure in-trace sync: apply the declared collectives over ``axis_name``.

        Honors ``dist_sync_fn`` (e.g. ``parallel.quantized_sync``) like the OO
        :meth:`sync` path does.

        The reserved ``"_update_count"`` key a :meth:`state` export carries is
        NOT a declared state: it is stripped before the collectives (applying
        a per-field reduction to the plain count leaf would stack it per rank,
        or crash under jit) and re-attached afterwards summed across ranks —
        the count of a synced state is the number of updates merged into it
        world-wide.
        """
        axis = axis_name or self.sync_axis
        count = state.get(self._STATE_COUNT_KEY)
        if count is not None:
            state = {k: v for k, v in state.items() if k != self._STATE_COUNT_KEY}
        if self.dist_sync_fn is not None:
            out = {k: self.dist_sync_fn(v, self._reductions.get(k), axis) for k, v in state.items()}
        else:
            out = sync_states(state, self._reductions, axis, qspecs=self._sync_qspecs())
        if count is not None:
            out[self._STATE_COUNT_KEY] = jax.lax.psum(jnp.asarray(count), axis)
        return out

    def merge_states(
        self, a: Dict[str, Any], b: Dict[str, Any], counts: Optional[Tuple[int, int]] = None
    ) -> Dict[str, Any]:
        """Merge two state pytrees per declared reductions (generalised Chan merge).

        ``counts`` gives the number of updates each side accumulated; with it,
        "mean" states merge count-weighted (the reference's running-mean formula,
        metric.py:399-431). Without counts, "mean" assumes both sides saw the same
        number of updates — subclasses needing exact merging under unequal counts
        carry explicit weight states (as the reference's MeanMetric does).
        """
        na, nb = counts if counts is not None else (1, 1)
        out: Dict[str, Any] = {}
        for attr in self._defaults:
            fx = self._reductions[attr]
            va, vb = a[attr], b[attr]
            if fx == "sum":
                out[attr] = va + vb
            elif fx == "mean":
                out[attr] = (na * va + nb * vb) / (na + nb)
            elif fx == "max":
                out[attr] = jnp.maximum(va, vb)
            elif fx == "min":
                out[attr] = jnp.minimum(va, vb)
            elif fx == "cat":
                if isinstance(va, list) or isinstance(vb, list):
                    out[attr] = list(va) + list(vb)
                else:
                    out[attr] = jnp.concatenate([jnp.atleast_1d(va), jnp.atleast_1d(vb)])
            elif fx is None and isinstance(va, list):
                out[attr] = list(va) + list(vb)
            elif callable(fx):
                out[attr] = fx(jnp.stack([jnp.asarray(va), jnp.asarray(vb)]))
            else:
                out[attr] = jnp.stack([jnp.atleast_1d(va), jnp.atleast_1d(vb)])
        return out

    def load_state(
        self,
        state: Dict[str, Any],
        update_count: Optional[int] = None,
        validate: str = "strict",
        check_finite: bool = False,
        sharded: Optional[bool] = None,
    ) -> None:
        """Install a state pytree as the live state (inverse of :meth:`state`).

        ``update_count`` restores the number of updates the state represents.
        When omitted, a count carried by the state itself (the reserved
        ``"_update_count"`` key every :meth:`state` export includes) is used,
        so ``m2.load_state(m1.state())`` round-trips the count without the
        caller passing it; with neither, the count falls back to exactly 1 (a
        restored state counts as updated so ``compute()`` does not warn, and a
        stale pre-load count on the target instance is never kept). The count
        weights ``"mean"``-reduced merges in ``forward`` after a resume.

        ``validate`` guards against corrupted resume checkpoints (ISSUE 2):
        ``"strict"`` (default) rejects structural/shape/dtype mismatches with
        :class:`StateCorruptionError` before anything is installed — the
        checks read metadata only, so the happy path adds zero device
        dispatches; ``"cast"`` converts dtype mismatches instead of raising;
        ``"off"`` restores the unchecked fast path. ``check_finite=True``
        additionally rejects NaN/Inf float accumulators (adds one reduction
        per float field). Validation is all-or-nothing: on any failure the
        live state is untouched.

        ``sharded=True`` installs a *sharded* state — the stacked per-device
        layout a deferred-reduction epoch loop carries (docs/SHARDING.md):
        each array field has a leading shard axis. The stack is kept as-is
        and folded per the declared reductions on demand (the next
        ``update``/``compute``/``sync``), so a mid-epoch checkpoint can be
        pushed straight back onto the mesh without losing per-shard locality.
        ``None`` (default) auto-detects via the reserved ``"_sharded_shards"``
        key a sharded :meth:`state` export carries.
        """
        if sharded is None:
            sharded = isinstance(state, dict) and state.get(self._STATE_SHARDS_KEY) is not None
        if not sharded:
            # class-sharded fields self-heal their layout first (dense or
            # differently-sharded snapshots re-split exactly — pure metadata
            # transforms), so validation below judges the adopted layout
            state = self._adopt_class_layouts(state)
        state = self.validate_state(state, mode=validate, check_finite=check_finite, sharded=sharded)
        carried = state.get(self._STATE_COUNT_KEY)
        if update_count is None and carried is not None:
            update_count = int(np.asarray(carried))
        # stage fully, then install — a raise mid-loop must not half-load
        staged: Dict[str, Any] = {}
        for k in self._defaults:
            if k not in state:
                raise obs.flighted(StateCorruptionError(f"state missing field {k!r}"), domain="checkpoint")
            v = state[k]
            staged[k] = list(v) if isinstance(v, (list, tuple)) else v
        num_shards: Optional[int] = None
        if sharded:
            for v in staged.values():
                if not isinstance(v, list) and getattr(jnp.asarray(v), "ndim", 0) >= 1:
                    num_shards = int(jnp.asarray(v).shape[0])
                    break
            if num_shards is None:
                raise obs.flighted(StateCorruptionError(
                    f"{type(self).__name__}: sharded=True but no array field carries a shard axis"
                ), domain="checkpoint")
        self._state.update(staged)
        self.__dict__["_state_escaped"] = True  # installed arrays have external aliases
        self._computed = None
        self._update_count = self._restored_count(update_count)
        self.__dict__["_pending_shards"] = num_shards
        if sharded:
            self.__dict__["_reduced"] = False

    @staticmethod
    def _restored_count(update_count: Optional[int], fallback: int = 1) -> int:
        """The single restore policy for ``load_state``'s update count: the
        explicit value when given, else ``fallback`` (default exactly 1 — a
        restored state counts as updated, and a stale pre-load count on the
        target instance is never kept). Wrappers whose exported state carries
        its own count (MinMax, Running) pass that count as ``fallback``."""
        return int(update_count) if update_count is not None else int(fallback)

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        """Restore default states (reference metric.py:679-694)."""
        self._update_count = 0
        self._computed = None
        for attr, default in self._defaults.items():
            if isinstance(default, list):
                self._state[attr] = []
            else:
                self._state[attr] = jnp.asarray(default)
        # fresh states alias _defaults (jnp.asarray is a no-op on jnp arrays):
        # the executor must copy before its next donation
        self.__dict__["_state_escaped"] = True
        self._cache = None
        self._is_synced = False
        self.__dict__["_reduced"] = True  # nothing accumulated, nothing pending
        self.__dict__["_pending_shards"] = None

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference metric.py:696-698)."""
        return copy.deepcopy(self)

    def laned(self, capacity: int = 8, max_capacity: Optional[int] = None, **kwargs: Any) -> Any:
        """A :class:`~torchmetrics_tpu.lanes.LanedMetric` stacking N
        independent copies of this metric's state along a lane axis, one
        compiled dispatch advancing every active session (docs/LANES.md).
        The wrapper holds a detached clone; this instance is untouched."""
        from torchmetrics_tpu.lanes import LanedMetric

        return LanedMetric(self, capacity=capacity, max_capacity=max_capacity, **kwargs)

    def windowed(self, window: int = 8, lateness: int = 0, **kwargs: Any) -> Any:
        """A :class:`~torchmetrics_tpu.windows.WindowedMetric` stacking W
        per-window copies of this metric's state along a ring axis: O(1)
        tumbling/sliding windows with watermark-bounded late-event routing
        (docs/STREAMING.md). The wrapper holds a detached clone; this
        instance is untouched. Compose with lanes as
        ``metric.windowed(W).laned(capacity)`` — window axis under the lane
        axis."""
        from torchmetrics_tpu.windows import WindowedMetric

        return WindowedMetric(self, window=window, lateness=lateness, **kwargs)

    def persistent(self, mode: bool = False) -> None:
        """Toggle persistence of all states (reference metric.py:840-843)."""
        for key in self._persistent:
            self._persistent[key] = mode

    def state_dict(self, destination: Optional[Dict[str, Any]] = None, prefix: str = "") -> Dict[str, Any]:
        """Serialize persistent states (reference metric.py:845-877)."""
        destination = destination if destination is not None else {}
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            current_val = self._state[key]
            if isinstance(current_val, list):
                destination[prefix + key] = [np.asarray(v) for v in current_val]
            else:
                destination[prefix + key] = np.asarray(current_val)
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True) -> None:
        """Restore states from :meth:`state_dict` output (reference metric.py:894-911)."""
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                value = state_dict[name]
                if isinstance(value, list):
                    self._state[key] = [jnp.asarray(v) for v in value]
                else:
                    self._state[key] = jnp.asarray(value)
            elif strict and self._persistent[key]:
                raise KeyError(f"Missing key {name!r} in state_dict")
        self.__dict__["_state_escaped"] = True
        self._computed = None

    def to(self, device) -> "Metric":
        """Move states to a device (the ``nn.Module.to`` analogue, metric.py:744+)."""
        for k, v in self._state.items():
            if isinstance(v, list):
                self._state[k] = [jax.device_put(el, device) for el in v]
            else:
                self._state[k] = jax.device_put(v, device)
        self._defaults = {
            k: ([jax.device_put(el, device) for el in v] if isinstance(v, list) else jax.device_put(v, device))
            for k, v in self._defaults.items()
        }
        self.__dict__["_state_escaped"] = True
        return self

    def set_dtype(self, dst_type) -> "Metric":
        """Explicitly cast float states to ``dst_type`` (reference metric.py:767-782)."""
        self._dtype_convert = True

        def _cast(v):
            return v.astype(dst_type) if isinstance(v, jnp.ndarray) and jnp.issubdtype(v.dtype, jnp.floating) else v

        for k, v in self._state.items():
            self._state[k] = [_cast(el) for el in v] if isinstance(v, list) else _cast(v)
        self._defaults = {
            k: ([_cast(el) for el in v] if isinstance(v, list) else _cast(v)) for k, v in self._defaults.items()
        }
        self.__dict__["_state_escaped"] = True
        self._dtype_convert = False
        return self

    # -------------------------------------------------------------- utilities
    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs to those accepted by this metric's update (metric.py:913-932)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        if exists_var_keyword:
            filtered_kwargs = kwargs
        return filtered_kwargs

    def __hash__(self) -> int:
        hash_vals = [type(self).__name__]
        for key in self._defaults:
            val = self._state[key]
            if isinstance(val, list):
                hash_vals.extend([np.asarray(v).tobytes() for v in val])
            else:
                hash_vals.append(np.asarray(val).tobytes())
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    def type(self, dst_type) -> "Metric":
        return self.set_dtype(dst_type)

    def float(self) -> "Metric":
        return self.set_dtype(jnp.float32)

    def double(self) -> "Metric":
        return self.set_dtype(jnp.float64)

    def half(self) -> "Metric":
        return self.set_dtype(jnp.bfloat16)

    # ----------------------------------------------------------- pickling
    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        # drop the wrapped bound methods; re-created in __setstate__ (metric.py:700-719)
        state.pop("update", None)
        state.pop("compute", None)
        # observers are process-local callbacks (autosavers, fault hooks): a
        # pickled/cloned copy must not inherit another instance's triggers
        state.pop("_update_observers", None)
        state.pop("_integrity_auditor", None)  # holds a lock + live-metric ref
        state.pop("_forward_depth", None)
        # the async-read clone and its inline verdict are process-local (and
        # keeping the clone would deep-copy it into every clone-of-a-clone)
        state.pop("_read_clone_cache", None)
        state.pop("_async_inline_reason_c", None)
        state.pop("_update_fn", None)
        state.pop("_compute_fn", None)
        state.pop("_update_signature", None)
        # the class-cell recovery mirror chains off this process's commit stream
        state.pop("_class_mirror", None)
        # compiled executables are process-local; a restored copy owns nothing
        state["_executor_obj"] = None
        state["_state_escaped"] = True
        state["_state_shared"] = False
        # jnp arrays pickle fine via numpy
        state["_state"] = {
            k: ([np.asarray(el) for el in v] if isinstance(v, list) else np.asarray(v)) for k, v in state["_state"].items()
        }
        state["_defaults"] = {
            k: ([np.asarray(el) for el in v] if isinstance(v, list) else np.asarray(v))
            for k, v in state["_defaults"].items()
        }
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.__dict__.setdefault("_executor_obj", None)
        self.__dict__.setdefault("_executor_enabled", None)
        self.__dict__.setdefault("_state_escaped", True)
        self.__dict__.setdefault("_state_shared", False)
        self.__dict__.setdefault("sync_timeout", None)
        self.__dict__.setdefault("on_sync_failure", "raise")
        self.__dict__.setdefault("sync_retries", None)
        self.__dict__.setdefault("_last_sync_ok", True)
        self.__dict__.setdefault("reduce_policy", default_reduce_policy())
        self.__dict__.setdefault("sync_precision", default_sync_precision())
        self.__dict__.setdefault("sync_quant_bits", _QUANT_DEFAULT_BITS)
        self.__dict__.setdefault("sync_quant_block", _QUANT_DEFAULT_BLOCK)
        self.__dict__.setdefault("_sync_precisions", {k: None for k in self.__dict__.get("_defaults", {})})
        self.__dict__.setdefault("state_sharding", "replicated")
        self.__dict__.setdefault("class_shards", default_class_shards())
        self.__dict__.setdefault("_state_shardings", {k: "replicated" for k in self.__dict__.get("_defaults", {})})
        self.__dict__.setdefault("_class_layouts", {})
        self.__dict__["_class_layouts"] = {
            k: (v if isinstance(v, ClassShardLayout) else ClassShardLayout(*v))
            for k, v in self.__dict__["_class_layouts"].items()
        }
        self.__dict__.setdefault("_reduced", True)
        self.__dict__.setdefault("_pending_shards", None)
        self.__dict__.setdefault("_last_reduce_us", None)
        self._state = {
            k: ([jnp.asarray(el) for el in v] if isinstance(v, list) else jnp.asarray(v)) for k, v in self._state.items()
        }
        self._defaults = {
            k: ([jnp.asarray(el) for el in v] if isinstance(v, list) else jnp.asarray(v))
            for k, v in self._defaults.items()
        }
        cls_update = type(self).update
        cls_compute = type(self).compute
        self._update_signature = inspect.signature(cls_update.__get__(self))
        self._update_fn = cls_update.__get__(self)
        self._compute_fn = cls_compute.__get__(self)
        object.__setattr__(self, "update", self._wrap_update(self._update_fn))
        object.__setattr__(self, "compute", self._wrap_compute(self._compute_fn))

    def __deepcopy__(self, memo: Optional[dict] = None) -> "Metric":
        cls = self.__class__
        new_obj = cls.__new__(cls)
        if memo is not None:
            memo[id(self)] = new_obj
        state = self.__getstate__()
        new_obj.__setstate__(copy.deepcopy(state, memo))
        return new_obj

    # --------------------------------------------------------------- plotting
    def plot(self, *args: Any, **kwargs: Any):
        """Default plot implementation (single/multi value) — see utils/plot.py."""
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = args[0] if args else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=kwargs.get("ax"),
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=type(self).__name__,
        )

    def _plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            name=type(self).__name__,
        )

    # --------------------------------------------------- composition algebra
    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.logical_not, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)

    def __getnewargs__(self) -> tuple:
        return tuple()

    def __iter__(self):
        raise NotImplementedError("Metrics does not support iteration.")


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Composition of two metrics (or metric and scalar) via an elementwise op.

    Reference metric.py:1109-1231: fans update/forward/reset/persistent out to
    child metrics and applies ``op`` to their compute results; its own sync is a
    no-op (children sync themselves).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy, BinaryPrecision
        >>> combo = BinaryAccuracy() + BinaryPrecision()  # CompositionalMetric
        >>> combo.update(jnp.asarray([0.2, 0.8, 0.3, 0.6]), jnp.asarray([0, 1, 1, 0]))
        >>> round(float(combo.compute()), 4)
        1.0
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, Array, None],
        metric_b: Union[Metric, float, int, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = jnp.asarray(metric_a) if isinstance(metric_a, (int, float, np.ndarray)) and not isinstance(metric_a, bool) else metric_a
        self.metric_b = jnp.asarray(metric_b) if isinstance(metric_b, (int, float, np.ndarray)) and not isinstance(metric_b, bool) else metric_b

    def _sync_dist(self, *args: Any, **kwargs: Any) -> None:
        pass  # children sync themselves

    def sync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            return None
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                return None
            return self.op(val_a)
        return self.op(val_a, val_b)

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else 'op'}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def __hash__(self) -> int:
        return object.__hash__(self)
