"""torchmetrics_tpu — TPU-native metrics framework on JAX/XLA.

A from-scratch re-design of the TorchMetrics capability surface
(reference: randombenj/torchmetrics) for TPU: state-as-pytree pure core,
lax collectives over device meshes for distributed sync, jit-traceable
update/compute, dual functional/modular API.
"""
__version__ = "0.1.0"

from torchmetrics_tpu.aggregation import (  # noqa: F401
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from torchmetrics_tpu.collections import MetricCollection  # noqa: F401
from torchmetrics_tpu.metric import CompositionalMetric, Metric  # noqa: F401
from torchmetrics_tpu import classification, functional, wrappers  # noqa: F401
from torchmetrics_tpu.classification import (  # noqa: F401
    AUROC,
    ROC,
    Accuracy,
    AveragePrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    ExactMatch,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    MatthewsCorrCoef,
    Precision,
    PrecisionAtFixedRecall,
    PrecisionRecallCurve,
    Recall,
    RecallAtFixedPrecision,
    SensitivityAtSpecificity,
    Specificity,
    SpecificityAtSensitivity,
    StatScores,
)
from torchmetrics_tpu.wrappers import (  # noqa: F401
    BootStrapper,
    ClasswiseWrapper,
    MetricTracker,
    MinMaxMetric,
    MultioutputWrapper,
    MultitaskWrapper,
    Running,
)
from torchmetrics_tpu import regression  # noqa: F401
from torchmetrics_tpu.regression import (  # noqa: F401
    ConcordanceCorrCoef,
    CosineSimilarity,
    CriticalSuccessIndex,
    ExplainedVariance,
    KendallRankCorrCoef,
    KLDivergence,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    PearsonCorrCoef,
    R2Score,
    RelativeSquaredError,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from torchmetrics_tpu import image, text  # noqa: F401
from torchmetrics_tpu.text import (  # noqa: F401
    BERTScore,
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    EditDistance,
    ExtendedEditDistance,
    InfoLM,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from torchmetrics_tpu import audio, clustering, detection, multimodal, nominal, retrieval  # noqa: F401
from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment, CLIPScore  # noqa: F401
from torchmetrics_tpu.clustering import (  # noqa: F401
    AdjustedMutualInfoScore,
    AdjustedRandScore,
    CalinskiHarabaszScore,
    CompletenessScore,
    DaviesBouldinScore,
    DunnIndex,
    FowlkesMallowsIndex,
    HomogeneityScore,
    MutualInfoScore,
    NormalizedMutualInfoScore,
    RandScore,
    VMeasureScore,
)
from torchmetrics_tpu.nominal import (  # noqa: F401
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)
from torchmetrics_tpu.detection import (  # noqa: F401
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
    MeanAveragePrecision,
    ModifiedPanopticQuality,
    PanopticQuality,
)
from torchmetrics_tpu.retrieval import (  # noqa: F401
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)
from torchmetrics_tpu.audio import (  # noqa: F401
    ComplexScaleInvariantSignalNoiseRatio,
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    SignalDistortionRatio,
    SignalNoiseRatio,
    SourceAggregatedSignalDistortionRatio,
)
from torchmetrics_tpu.image import (  # noqa: F401
    ErrorRelativeGlobalDimensionlessSynthesis,
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    PeakSignalNoiseRatioWithBlockedEffect,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)
from torchmetrics_tpu.classification import BinaryFairness, BinaryGroupStatRates, Dice  # noqa: F401
from torchmetrics_tpu.wrappers import FeatureShare  # noqa: F401
