from torchmetrics_tpu.retrieval.base import RetrievalMetric  # noqa: F401
from torchmetrics_tpu.retrieval.metrics import (  # noqa: F401
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)

__all__ = [
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
]
