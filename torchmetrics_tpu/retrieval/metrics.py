"""Modular retrieval metrics (reference retrieval/*.py, one class per file there).

Each subclass binds one padded kernel; RetrievalPrecisionRecallCurve overrides
``compute`` since it returns curves rather than per-query scalars.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.retrieval._padded import (
    auroc_padded,
    average_precision_padded,
    fall_out_padded,
    hit_rate_padded,
    ndcg_padded,
    precision_padded,
    precision_recall_curve_padded,
    r_precision_padded,
    rank_by_preds,
    recall_padded,
    reciprocal_rank_padded,
)
from torchmetrics_tpu.functional.retrieval.metrics import _check_top_k
from torchmetrics_tpu.retrieval.base import RetrievalMetric, _retrieval_aggregate


class _TopKRetrievalMetric(RetrievalMetric):
    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _check_top_k(top_k)
        self.top_k = top_k


class RetrievalMAP(_TopKRetrievalMetric):
    """Mean average precision (reference retrieval/average_precision.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalMAP()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return average_precision_padded(ranked_target, counts, self.top_k)


class RetrievalMRR(_TopKRetrievalMetric):
    """Mean reciprocal rank (reference retrieval/reciprocal_rank.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalMRR()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return reciprocal_rank_padded(ranked_target, counts, self.top_k)


class RetrievalPrecision(RetrievalMetric):
    """Precision@k (reference retrieval/precision.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecision
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalPrecision()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        0.4167
    """

    def __init__(self, top_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _check_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return precision_padded(ranked_target, counts, self.top_k, self.adaptive_k)


class RetrievalRecall(_TopKRetrievalMetric):
    """Recall@k (reference retrieval/recall.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalRecall
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalRecall()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return recall_padded(ranked_target, counts, self.top_k)


class RetrievalFallOut(_TopKRetrievalMetric):
    """Fall-out@k (reference retrieval/fall_out.py). Empty queries = no NEGATIVE target.

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalFallOut
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalFallOut()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    higher_is_better = False
    _empty_target_kind = "negative"

    def _empty_mask(self, target_pad: Array, counts: Array) -> Array:
        pos = jnp.arange(target_pad.shape[-1])[None, :]
        valid = pos < counts[:, None]
        return jnp.sum((1.0 - target_pad) * valid, axis=-1) == 0

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return fall_out_padded(ranked_target, counts, self.top_k)


class RetrievalHitRate(_TopKRetrievalMetric):
    """Hit rate@k (reference retrieval/hit_rate.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalHitRate
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalHitRate()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return hit_rate_padded(ranked_target, counts, self.top_k)


class RetrievalRPrecision(RetrievalMetric):
    """R-precision (reference retrieval/r_precision.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalRPrecision
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalRPrecision()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return r_precision_padded(ranked_target, counts)


class RetrievalNormalizedDCG(_TopKRetrievalMetric):
    """nDCG with tie-averaged gains (reference retrieval/ndcg.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalNormalizedDCG
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalNormalizedDCG()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    allow_non_binary_target = True

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        return ndcg_padded(ranked_preds, ranked_target, counts, self.top_k)


class RetrievalAUROC(_TopKRetrievalMetric):
    """Per-query AUROC over retrieved docs (reference retrieval/auroc.py).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalAUROC
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalAUROC()
        >>> m.update(preds, target, indexes=indexes)
        >>> round(float(m.compute()), 4)
        1.0
    """

    def __init__(self, top_k: Optional[int] = None, max_fpr: Optional[float] = None, **kwargs: Any) -> None:
        super().__init__(top_k=top_k, **kwargs)
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.max_fpr = max_fpr

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        if self.max_fpr is not None:
            # partial AUC needs per-query ROC curves: evaluate query-by-query on host
            from torchmetrics_tpu.functional.classification.auroc import binary_auroc

            values = []
            for q in range(ranked_target.shape[0]):
                n = int(counts[q])
                k = n if self.top_k is None else min(self.top_k, n)
                values.append(
                    binary_auroc(ranked_preds[q, :k], ranked_target[q, :k].astype(jnp.int32), max_fpr=self.max_fpr)
                )
            return jnp.stack(values)
        return auroc_padded(ranked_preds, ranked_target, counts, self.top_k)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision/recall@k curves (reference retrieval/precision_recall_curve.py:63-255).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalPrecisionRecallCurve
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalPrecisionRecallCurve()
        >>> m.update(preds, target, indexes=indexes)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in m.compute()]
        [[1.0, 0.5, 0.33329999446868896], [1.0, 1.0, 1.0], [1, 2, 3]]
    """

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            empty_target_action=empty_target_action, ignore_index=ignore_index, aggregation=aggregation, **kwargs
        )
        if (max_k is not None) and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        self.max_k = max_k
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.adaptive_k = adaptive_k

    def compute(self) -> Tuple[Array, Array, Array]:
        preds_pad, target_pad, counts = self._grouped_state()
        _, ranked_target = rank_by_preds(preds_pad, target_pad)
        max_k = self.max_k if self.max_k is not None else int(counts.max())

        precisions, recalls, top_k = precision_recall_curve_padded(ranked_target, counts, max_k, self.adaptive_k)

        empty = self._empty_mask(target_pad, counts)
        precisions = self._apply_empty_target_action(precisions, empty)
        recalls = self._apply_empty_target_action(recalls, empty)
        if precisions is None or recalls is None:
            z = jnp.zeros(max_k)
            return z, z, top_k

        precision = _retrieval_aggregate(precisions, self.aggregation, dim=0)
        recall = _retrieval_aggregate(recalls, self.aggregation, dim=0)
        return precision, recall, top_k

    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        raise NotImplementedError  # compute() is fully overridden


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Max recall with precision >= min_precision (reference precision_recall_curve.py:296-391).

    Example:
        >>> from torchmetrics_tpu.retrieval import RetrievalRecallAtFixedPrecision
        >>> import jax.numpy as jnp
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3])
        >>> target = jnp.asarray([False, False, True, False, True])
        >>> m = RetrievalRecallAtFixedPrecision()
        >>> m.update(preds, target, indexes=indexes)
        >>> [jnp.round(jnp.asarray(v), 4).tolist() for v in m.compute()]
        [1.0, 3]
    """

    def __init__(self, min_precision: float = 0.0, max_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(max_k=max_k, **kwargs)
        if not isinstance(min_precision, float) or not 0.0 <= min_precision <= 1.0:
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precisions, recalls, top_k = super().compute()
        ok = precisions >= self.min_precision
        masked_recall = jnp.where(ok, recalls, -jnp.inf)
        # max recall, breaking ties by larger k (reference max() over (r, k) tuples)
        best_recall = jnp.max(masked_recall)
        if not bool(jnp.isfinite(best_recall)) or float(best_recall) == 0.0:
            return jnp.asarray(0.0), jnp.asarray(int(top_k.shape[0]))
        is_best = masked_recall == best_recall
        best_k = jnp.max(jnp.where(is_best, top_k, 0))
        return best_recall, best_k
