"""RetrievalMetric base (reference retrieval/base.py:43-180).

State is three growing ``cat`` lists (indexes/preds/target). At compute time the
ragged per-query groups become one static padded grid evaluated by a single
batched kernel (see functional/retrieval/_padded.py) — replacing the reference's
sort + split + per-query Python loop with one XLA dispatch.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Optional, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.retrieval._padded import pad_by_query, rank_by_preds
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.checks import _is_concrete
from torchmetrics_tpu.utils.data import compact_readout, compact_scatter, dim_zero_cat


def _retrieval_aggregate(values: Array, aggregation: Union[str, Callable], dim: int = 0) -> Array:
    """Aggregate per-query values (reference retrieval/base.py:24-40)."""
    if aggregation == "mean":
        return jnp.mean(values, axis=dim)
    if aggregation == "median":
        # torch.median semantics: lower of the two middle elements, not their mean
        n = values.shape[dim]
        return jnp.take(jnp.sort(values, axis=dim), (n - 1) // 2, axis=dim)
    if aggregation == "min":
        return jnp.min(values, axis=dim)
    if aggregation == "max":
        return jnp.max(values, axis=dim)
    return aggregation(values, dim=dim)


class RetrievalMetric(Metric, ABC):
    """Base for query-grouped metrics.

    Update accepts ``(preds, target, indexes)`` of equal shape; compute groups
    by query id and averages the per-query ``_metric_padded`` values, honoring
    ``empty_target_action`` in {'error','skip','neg','pos'} for queries with no
    positive target.
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    allow_non_binary_target: bool = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        """``capacity`` (TPU extension, SURVEY §7 hard part 1b): fixed (N,)
        sample buffers instead of growing lists, making ``update``/
        ``functional_update`` jit/shard_map-traceable with static shapes; the
        first N un-ignored samples are kept, overflow warns at compute."""
        super().__init__(**kwargs)
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index

        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable function"
                f"which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        if capacity is not None and (not isinstance(capacity, int) or capacity < 1):
            raise ValueError(f"Argument `capacity` expected to be a positive integer, got {capacity}")
        self.capacity = capacity
        # sample buffers are indexed by ARRIVAL ORDER, not by class/bucket —
        # class-axis sharding is meaningless for them (and "cat"/None growing
        # reductions are ineligible anyway); the explicit pin keeps the layout
        # deterministic under a TORCHMETRICS_TPU_STATE_SHARDING=class_axis
        # process default (docs/SHARDING.md eligibility table)
        if capacity is not None:
            self.add_state("indexes_buffer", default=jnp.zeros(capacity, dtype=jnp.int32), dist_reduce_fx="cat", state_sharding="replicated")
            self.add_state("preds_buffer", default=jnp.zeros(capacity, dtype=jnp.float32), dist_reduce_fx="cat", state_sharding="replicated")
            self.add_state("target_buffer", default=jnp.zeros(capacity, dtype=jnp.float32), dist_reduce_fx="cat", state_sharding="replicated")
            self.add_state("valid_buffer", default=jnp.zeros(capacity, dtype=bool), dist_reduce_fx="cat", state_sharding="replicated")
            self.add_state("sample_count", default=jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        else:
            self.add_state("indexes", default=[], dist_reduce_fx=None, state_sharding="replicated")
            self.add_state("preds", default=[], dist_reduce_fx=None, state_sharding="replicated")
            self.add_state("target", default=[], dist_reduce_fx=None, state_sharding="replicated")

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes = jnp.asarray(indexes)
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if indexes.shape != preds.shape or preds.shape != target.shape:
            raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
        if not jnp.issubdtype(indexes.dtype, jnp.integer):
            raise ValueError("`indexes` must be a tensor of long integers")
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError("`preds` must be a tensor of floats")

        if self.capacity is not None:
            # trace-safe path: keep a validity mask instead of boolean indexing
            valid = (
                jnp.ones(indexes.size, dtype=bool)
                if self.ignore_index is None
                else (target != self.ignore_index).reshape(-1)
            )
            if _is_concrete(target):
                # reference semantics: emptiness judged AFTER ignore_index
                # filtering (reference utilities/checks.py:573-580)
                if indexes.size == 0 or not bool(jnp.any(valid)):
                    raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
                if not self.allow_non_binary_target:
                    t = target.reshape(-1)
                    if bool(jnp.any(((t != 0) & (t != 1)) & valid)):
                        raise ValueError("`target` must contain binary values")
            bufs = (self.indexes_buffer, self.preds_buffer, self.target_buffer, self.valid_buffer)
            (
                (self.indexes_buffer, self.preds_buffer, self.target_buffer, self.valid_buffer),
                self.sample_count,
            ) = compact_scatter(bufs, (indexes, preds, target, valid), valid, self.sample_count)
            return

        if self.ignore_index is not None:
            valid = (target != self.ignore_index).reshape(-1)
            indexes = indexes.reshape(-1)[valid]
            preds = preds.reshape(-1)[valid]
            target = target.reshape(-1)[valid]
        if indexes.size == 0:
            raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
        if not self.allow_non_binary_target and bool(jnp.any((target != 0) & (target != 1))):
            raise ValueError("`target` must contain binary values")

        self.indexes.append(indexes.reshape(-1).astype(jnp.int32))
        self.preds.append(preds.reshape(-1).astype(jnp.float32))
        self.target.append(target.reshape(-1).astype(jnp.float32))

    _empty_target_kind: str = "positive"  # which class being absent makes a query "empty"

    def _grouped_state(self):
        """Concatenate states and pack into the padded per-query grid."""
        if self.capacity is not None:
            indexes, preds, target = compact_readout(
                (self.indexes_buffer, self.preds_buffer, self.target_buffer),
                self.valid_buffer,
                self.sample_count,
                type(self).__name__,
            )
        else:
            indexes = dim_zero_cat(self.indexes)
            preds = dim_zero_cat(self.preds)
            target = dim_zero_cat(self.target)
        return pad_by_query(indexes, preds, target)

    def _empty_mask(self, target_pad: Array, counts: Array) -> Array:
        """(Q,) mask of queries with no positive target (overridable, e.g. fall-out)."""
        return jnp.sum(target_pad, axis=-1) == 0

    def _apply_empty_target_action(self, values: Array, empty: Array) -> Optional[Array]:
        """Resolve empty queries per ``empty_target_action``.

        ``values`` is (Q,) or (Q, K) (curves). Returns None when 'skip' drops
        every query — callers substitute their zero result.
        """
        if self.empty_target_action == "error" and bool(jnp.any(empty)):
            raise ValueError(
                f"`compute` method was provided with a query with no {self._empty_target_kind} target."
            )
        mask = empty if values.ndim == 1 else empty[:, None]
        if self.empty_target_action == "pos":
            return jnp.where(mask, 1.0, values)
        if self.empty_target_action == "neg":
            return jnp.where(mask, 0.0, values)
        if self.empty_target_action == "skip":
            keep = ~empty
            if not bool(jnp.any(keep)):
                return None
            return values[keep]
        return values

    def compute(self) -> Array:
        preds_pad, target_pad, counts = self._grouped_state()
        ranked_preds, ranked_target = rank_by_preds(preds_pad, target_pad)
        values = self._metric_padded(ranked_preds, ranked_target, counts)
        values = self._apply_empty_target_action(values, self._empty_mask(target_pad, counts))
        if values is None:
            return jnp.asarray(0.0)
        return _retrieval_aggregate(values, self.aggregation)

    @abstractmethod
    def _metric_padded(self, ranked_preds: Array, ranked_target: Array, counts: Array) -> Array:
        """Per-query metric over the ranked padded grid -> (num_queries,)."""
