"""MetricCollection with compute groups (reference collections.py, 664 LoC).

Accepts a list/dict/kwargs of metrics, renames outputs with prefix/postfix, and
filters kwargs per metric. **Compute groups** — the flagship optimization
(reference :228-308): after the first update, metrics whose post-update states
compare equal are merged into groups; thereafter only the group leader gets
``update`` and followers hold *references* to the leader's state. jnp arrays are
immutable, so "reference" sharing is simply pointing followers' state dicts at
the same arrays after each leader update — no aliasing hazards, and the
copy-on-access dance of the reference (:515-549) is unnecessary by construction.
"""
from __future__ import annotations

import os
from copy import deepcopy
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.parallel.sync import (
    REDUCE_POLICIES,
    init_sharded_states,
    local_accumulate_spec,
    sync_states,
    unshard_local_state,
)
from torchmetrics_tpu.utils.data import _flatten_dict
from torchmetrics_tpu.utils.prints import rank_zero_warn

_PREFIX_SUFFIX_ERROR = "Expected input `{}` to be a string, but got {}"


class MetricCollection:
    """Dict-like collection of metrics sharing update calls.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection
        >>> from torchmetrics_tpu.classification import BinaryAccuracy, BinaryPrecision
        >>> collection = MetricCollection([BinaryAccuracy(), BinaryPrecision()])
        >>> collection.update(jnp.asarray([0.2, 0.8, 0.3, 0.6]), jnp.asarray([0, 1, 1, 0]))
        >>> {k: round(float(v), 4) for k, v in collection.compute().items()}
        {'BinaryAccuracy': 0.5, 'BinaryPrecision': 0.5}

    Args:
        metrics: single metric, list/tuple of metrics, or dict name→metric.
        prefix / postfix: added to each output key.
        compute_groups: True (auto-detect), False (disable), or explicit list of
            lists of metric names.
        executor: route eager ``update``/``forward`` through ONE fused,
            donated-state compiled call covering every compute group
            (ops/executor.py). ``None`` (default) follows the
            ``TORCHMETRICS_TPU_EXECUTOR`` env flag; ``False`` restores the
            per-metric eager loop (members may still use their own executors).
        reduce: reduction policy applied to EVERY member: ``"step"`` keeps
            per-step collective semantics, ``"deferred"`` accumulates locally
            and applies each declared ``dist_reduce_fx`` exactly once at
            ``compute()``/``sync()`` time (docs/SHARDING.md). ``None``
            (default) leaves each member's own policy (which follows the
            ``TORCHMETRICS_TPU_REDUCE`` env var).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu import MetricCollection, Accuracy, Precision
        >>> coll = MetricCollection({
        ...     "acc": Accuracy(task="binary"),
        ...     "prec": Precision(task="binary"),
        ... })
        >>> coll.update(jnp.asarray([0.9, 0.2, 0.8, 0.4]), jnp.asarray([1, 0, 0, 1]))
        >>> {k: round(float(v), 4) for k, v in sorted(coll.compute().items())}
        {'acc': 0.5, 'prec': 0.5}
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
        executor: Optional[bool] = None,
        reduce: Optional[str] = None,
    ) -> None:
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked = False
        self._state_is_copy = False
        if executor is not None and not isinstance(executor, bool):
            raise ValueError(f"Expected keyword argument `executor` to be a `bool` but got {executor}")
        self._executor_enabled = executor
        self._executor_obj: Optional[Any] = None
        if reduce is not None and reduce not in REDUCE_POLICIES:
            raise ValueError(f"Expected keyword argument `reduce` to be one of {REDUCE_POLICIES} but got {reduce}")
        self.reduce_policy = reduce
        self._modules: Dict[str, Metric] = {}
        self.add_metrics(metrics, *additional_metrics)

    def _get_executor(self):
        """The lazily-built fused collection executor, or None when disabled."""
        if self._executor_enabled is False:
            return None
        from torchmetrics_tpu.ops import executor as _executor_mod

        if self._executor_enabled is None and not _executor_mod.executor_enabled_default():
            return None
        if self._executor_obj is None:
            self._executor_obj = _executor_mod.CollectionExecutor(self)
        return self._executor_obj

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["_executor_obj"] = None  # compiled executables are process-local
        # observers are process-local callbacks (autosavers, fault hooks)
        state.pop("_update_observers", None)
        state.pop("_read_clone_cache", None)  # async-read clone is process-local
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self.__dict__.setdefault("_executor_obj", None)
        self.__dict__.setdefault("_executor_enabled", None)
        self.__dict__.setdefault("reduce_policy", None)

    # --------------------------------------------------------------- plumbing
    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(_PREFIX_SUFFIX_ERROR.format(name, arg))

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Add metrics to the collection (reference collections.py:423-462)."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )
        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[f"{name}_{k}"] = v
        elif isinstance(metrics, (list, tuple)):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `torchmetrics_tpu.Metric` or `torchmetrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self._modules:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self._modules[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self._modules[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")
        if self.reduce_policy is not None:
            for name, m in self._modules.items():
                if self.reduce_policy == "deferred" and m.dist_sync_on_step:
                    raise ValueError(
                        f"Member {name!r} has dist_sync_on_step=True, which conflicts with the"
                        " collection's reduce='deferred' policy (a per-step sync IS the step policy)"
                    )
                m.reduce_policy = self.reduce_policy
        self._groups_checked = False
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    def _init_compute_groups(self) -> None:
        """Initialize compute groups (reference collections.py:462-482)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = dict(enumerate(self._enable_compute_groups))
            for v in self._groups.values():
                for metric in v:
                    if metric not in self._modules:
                        raise ValueError(
                            f"Input {metric} in `compute_groups` argument does not match a metric in the collection."
                        )
            self._groups_checked = True
        else:
            # start with all metrics in their own group; merged after first update
            self._groups = {i: [name] for i, name in enumerate(self._modules)}

    # ----------------------------------------------------------- dict protocol
    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return self._modules.keys()
        return [self._set_name(k) for k in self._modules]

    def values(self, copy_state: bool = False) -> Iterable[Metric]:
        return self._modules.values()

    def items(self, keep_base: bool = False, copy_state: bool = False) -> Iterable[Tuple[str, Metric]]:
        if keep_base:
            return self._modules.items()
        return [(self._set_name(k), v) for k, v in self._modules.items()]

    def __getitem__(self, key: str) -> Metric:
        if key in self._modules:
            return self._modules[key]
        # try without prefix/postfix
        for k in self._modules:
            if self._set_name(k) == key:
                return self._modules[k]
        raise KeyError(key)

    def __len__(self) -> int:
        return len(self._modules)

    def __iter__(self):
        return iter(self.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._modules or key in self.keys()

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    # ------------------------------------------------------ update observers
    @property
    def update_count(self) -> int:
        """Updates committed into the collection: the max member count (group
        leaders advance in lockstep, so this is the shared step count)."""
        return max((m.update_count for m in self._modules.values()), default=0)

    def add_update_observer(self, callback: Any) -> Any:
        """Register ``callback(collection)`` to fire once after every committed
        collection-level ``update``/``forward`` — both the fused-executor path
        (where member ``update`` never runs) and the per-group loop. The
        autosave trigger point (io/checkpoint.py). Returns a detach function."""
        observers = self.__dict__.setdefault("_update_observers", [])
        observers.append(callback)

        def detach() -> None:
            obs = self.__dict__.get("_update_observers")
            if obs is not None and callback in obs:
                obs.remove(callback)

        return detach

    def _notify_update(self) -> None:
        observers = self.__dict__.get("_update_observers")
        if observers:
            for callback in tuple(observers):
                callback(self)

    # ------------------------------------------------------------- metric API
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each metric once per compute group (reference :200-226).

        Once groups are resolved, the fused executor runs EVERY group's update
        as one compiled, donated-state call; when it cannot (disabled, an
        untraceable leader, exotic inputs), the per-group loop below runs and
        each leader still benefits from its own per-metric executor."""
        if self._groups_checked:
            ex = self._get_executor()
            if ex is not None and ex.run_update(args, kwargs):
                self._compute_groups_create_state_ref()
                self._notify_update()
                return
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                m0.update(*args, **m0._filter_kwargs(**kwargs))
            self._compute_groups_create_state_ref()
        else:
            for m in self._modules.values():
                m.update(*args, **m._filter_kwargs(**kwargs))
            if self._enable_compute_groups:
                self._merge_compute_groups()
                self._compute_groups_create_state_ref()
                self._groups_checked = True
        self._notify_update()

    def _merge_compute_groups(self, trial_states: Optional[Dict[str, Dict[str, Any]]] = None) -> None:
        """Union groups whose states compare equal (reference :228-262), O(n²).

        With ``trial_states`` (name → state pytree) the comparison runs on those
        pytrees instead of the metrics' live state — used by
        :meth:`resolve_compute_groups` to probe grouping without mutating anything.
        """
        num_groups = len(self._groups)
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 == cg_idx2:
                        continue
                    n1, n2 = cg_members1[0], cg_members2[0]
                    metric1 = self._modules[n1]
                    metric2 = self._modules[n2]
                    if self._equal_metric_states(
                        metric1,
                        metric2,
                        None if trial_states is None else trial_states[n1],
                        None if trial_states is None else trial_states[n2],
                    ):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            if num_groups == len(self._groups):
                break
            num_groups = len(self._groups)
        self._groups = {i: v for i, v in enumerate(self._groups.values())}

    @staticmethod
    def _equal_metric_states(
        metric1: Metric,
        metric2: Metric,
        state1: Optional[Dict[str, Any]] = None,
        state2: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """True if both metrics hold identical states (reference :264-287)."""
        if not metric1._defaults or not metric2._defaults:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        if metric1._reductions != metric2._reductions:
            return False
        state1 = state1 if state1 is not None else metric1._state
        state2 = state2 if state2 is not None else metric2._state
        for key in metric1._defaults:
            s1 = state1[key]
            s2 = state2[key]
            if type(s1) != type(s2):  # noqa: E721
                return False
            if isinstance(s1, list):
                if len(s1) != len(s2):
                    return False
                if not all(a.shape == b.shape and bool(jnp.array_equal(a, b)) for a, b in zip(s1, s2)):
                    return False
            else:
                if s1.shape != s2.shape or not bool(jnp.array_equal(s1, s2)):
                    return False
        return True

    def _compute_groups_create_state_ref(self, copy: bool = False) -> None:
        """Point follower states at the leader's arrays (reference :289-308)."""
        for cg in self._groups.values():
            m0 = self._modules[cg[0]]
            if len(cg) > 1:
                # the group's arrays are intentionally aliased: the per-metric
                # executor must never donate them (the collection's fused
                # executor manages donation for the group as a whole)
                m0.__dict__["_state_shared"] = True
            for name in cg[1:]:
                follower = self._modules[name]
                for state in m0._defaults:
                    val = m0._state[state]
                    follower._state[state] = list(val) if isinstance(val, list) else val
                follower._update_count = m0._update_count
                follower._computed = None
                follower.__dict__["_state_shared"] = True
                # followers read the leader's arrays: their deferred-reduction
                # flags must describe the same (shared) state
                follower.__dict__["_reduced"] = m0.__dict__.get("_reduced", True)
                follower.__dict__["_pending_shards"] = m0.__dict__.get("_pending_shards")
        self._state_is_copy = copy

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Batch values for every metric, one shared update per compute group.

        Goes beyond the reference (which disables groups under forward,
        collections.py:200-226 docs): for groups whose members are all
        ``full_state_update=False``, the leader's batch state is computed once
        and every member derives both its batch value and its global-state merge
        from it — the 2-3× update saving applies to the training-step path too.
        """
        res: Dict[str, Any] = {}
        if self._groups_checked and self._enable_compute_groups:
            ex = self._get_executor()
            if ex is not None:
                fused = ex.run_forward(args, kwargs)
                if fused is not None:
                    self._compute_groups_create_state_ref()
                    out, _ = _flatten_dict({self._set_name(k): v for k, v in fused.items()})
                    self._notify_update()
                    return out
            for cg in self._groups.values():
                members = [(n, self._modules[n]) for n in cg]
                m0 = members[0][1]
                if len(cg) > 1 and all(
                    m.full_state_update is False and not m.dist_sync_on_step for _, m in members
                ):
                    # transactional like Metric._forward_reduce_state_update: a
                    # raise from the batch update, merge, or any member's
                    # compute restores the leader's pre-call state and count
                    global_state = m0._copy_state_dict()
                    pre_count, pre_computed = m0._update_count, m0._computed
                    try:
                        batch_state = m0.functional_update(m0.functional_init(), *args, **m0._filter_kwargs(**kwargs))
                        m0._state = {k: (list(v) if isinstance(v, list) else v) for k, v in batch_state.items()}
                        m0._update_count += 1
                        m0._reduce_states(global_state)
                        m0._mark_unreduced()
                        m0._computed = None
                        for name, m in members:
                            res[name] = m.functional_compute(batch_state)
                    except BaseException:
                        m0._rollback(
                            {k: (list(v) if isinstance(v, list) else v) for k, v in global_state.items()},
                            pre_count,
                            pre_computed,
                        )
                        raise
                else:
                    for name, m in members:
                        res[name] = m(*args, **m._filter_kwargs(**kwargs))
            self._compute_groups_create_state_ref()
        else:
            res = {k: m(*args, **m._filter_kwargs(**kwargs)) for k, m in self._modules.items()}
            if self._enable_compute_groups and not self._groups_checked:
                self._merge_compute_groups()
                self._compute_groups_create_state_ref()
                self._groups_checked = True
        res, _ = _flatten_dict({self._set_name(k): v for k, v in res.items()})
        self._notify_update()
        return res

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def compute(self) -> Dict[str, Any]:
        return self._compute_and_reduce("compute")

    # ----------------------------------------------------- asynchronous reads
    def compute_async(self) -> Any:
        """Non-blocking :meth:`compute`: one
        :class:`~torchmetrics_tpu.ops.async_read.MetricFuture` resolving to
        the full renamed/flattened result dict a blocking ``compute()`` would
        return for every member's state as of this call (docs/ASYNC.md).

        Each member contributes its own caller-side snapshot (so the whole
        collection reads consistently against later updates) and the worker
        runs the member bodies as ONE pipeline job — a per-step read of a
        5-metric collection costs one queue slot, not five."""
        from torchmetrics_tpu import obs
        from torchmetrics_tpu.ops import async_read as _async

        owner = type(self).__name__
        with obs.span(obs.SPAN_COMPUTE_ASYNC, suffix=owner):
            bodies = {name: m._prepare_async_read() for name, m in self._modules.items()}

            def job() -> Dict[str, Any]:
                return self._flatten_results({name: body() for name, body in bodies.items()})

            return _async.get_pipeline().submit(
                job, owner=owner, submitted_count=int(self.update_count)
            )

    def sync_async(self, axis_name: Any = None) -> Any:
        """Non-blocking read-side :meth:`sync`: a future resolving to
        ``{member_name: synced_state_pytree}`` (base names, every array
        ready), computed from each member's state as of this call. The live
        collection is never mutated — see ``Metric.sync_async``."""
        from torchmetrics_tpu import obs
        from torchmetrics_tpu.ops import async_read as _async

        owner = type(self).__name__
        with obs.span(obs.SPAN_COMPUTE_ASYNC, suffix=owner, kind="sync"):
            bodies = {name: m._prepare_async_sync(axis_name) for name, m in self._modules.items()}

            def job() -> Dict[str, Any]:
                return {name: body() for name, body in bodies.items()}

            return _async.get_pipeline().submit(
                job, owner=owner, submitted_count=int(self.update_count)
            )

    def _compute_and_reduce(self, method_name: str) -> Dict[str, Any]:
        """Per metric compute/forward, flatten dict results (reference :314-359)."""
        result = {}
        for k, m in self._modules.items():
            res = getattr(m, method_name)()
            result[k] = res
        return self._flatten_results(result)

    def _flatten_results(self, result: Dict[str, Any]) -> Dict[str, Any]:
        """Flatten dict-valued metric results with prefix dedup (reference :340-359)."""
        _, duplicates = _flatten_dict({k: v for k, v in result.items() if isinstance(v, dict)})
        flat = {}
        for k, res in result.items():
            if isinstance(res, dict):
                for sub_k, sub_v in res.items():
                    flat[f"{self._set_name(k)}_{sub_k}" if duplicates else self._set_name(sub_k)] = sub_v
            else:
                flat[self._set_name(k)] = res
        return flat

    # ------------------------------------------------------ pure/functional API
    #
    # The in-trace analogue of the OO path: collection states live in a pytree
    # keyed by compute-group leader, so a jitted/shard_map'd train step pays one
    # `update` and one set of collectives per GROUP, not per metric — the
    # reference's flagship 2-3x compute-group saving
    # (reference collections.py:228-308, docs/source/pages/overview.rst:392-397)
    # carried into the compiled-step world where the OO runtime probe can't go.
    #
    # Auto-grouping compares post-update states, which is impossible on tracers;
    # call `resolve_compute_groups(example_batch)` once, eagerly, before tracing
    # (or pass an explicit `compute_groups=[[...]]` list at construction).

    def resolve_compute_groups(self, *args: Any, **kwargs: Any) -> Dict[int, List[str]]:
        """Eagerly resolve compute groups from one concrete example batch.

        Runs every metric's pure ``functional_update`` on a fresh default state
        (live metric state is untouched) and unions metrics whose resulting
        states compare equal — the same probe the OO ``update`` path performs on
        its first call (reference collections.py:228-262), made explicit so it
        can happen host-side before ``jit`` tracing. Idempotent.

        Example:
            >>> import jax, jax.numpy as jnp
            >>> from torchmetrics_tpu import MetricCollection
            >>> from torchmetrics_tpu.classification import MulticlassF1Score, MulticlassRecall
            >>> coll = MetricCollection([MulticlassF1Score(num_classes=3), MulticlassRecall(num_classes=3)])
            >>> preds, target = jnp.asarray([0, 1, 2, 1]), jnp.asarray([0, 2, 2, 1])
            >>> groups = coll.resolve_compute_groups(preds, target)
            >>> sorted(len(g) for g in groups.values())  # f1/recall share one stat-scores state
            [2]
            >>> states = coll.functional_init()
            >>> states = jax.jit(coll.functional_update)(states, preds, target)
            >>> {k: round(float(v), 4) for k, v in sorted(coll.functional_compute(states).items())}
            {'MulticlassF1Score': 0.7778, 'MulticlassRecall': 0.8333}
        """
        if self._enable_compute_groups and not self._groups_checked:
            trial = {
                name: m.functional_update(m.functional_init(), *args, **m._filter_kwargs(**kwargs))
                for name, m in self._modules.items()
            }
            self._merge_compute_groups(trial_states=trial)
            self._groups_checked = True
        return self._groups

    def functional_init(self) -> Dict[str, Dict[str, Any]]:
        """Fresh default states, one pytree per compute-group leader."""
        return {cg[0]: self._modules[cg[0]].functional_init() for cg in self._groups.values()}

    # -------------------------------------------------- compile-ahead surface
    def warmup(
        self,
        batch_specs: Any,
        forward: bool = False,
        ladder: bool = True,
        background: bool = False,
    ) -> Any:
        """Precompile the fused executables ``batch_specs``-shaped traffic
        will hit (docs/EXECUTOR.md "Compile-ahead & persistent cache").

        Resolves compute groups from the first spec (zero-filled dummies —
        live state untouched), then warms ONE fused executable per distinct
        shape/bucket covering every group, exactly what
        ``update``/``forward`` traffic will dispatch. See
        :meth:`Metric.warmup` for spec forms, the ladder, and
        ``background=True`` semantics.

        Example::

            coll.warmup([(jax.ShapeDtypeStruct((1024, 10), jnp.float32),
                          jax.ShapeDtypeStruct((1024,), jnp.int32))], forward=True)
        """
        from torchmetrics_tpu.ops.executor import _normalize_warmup_specs

        specs = _normalize_warmup_specs(batch_specs)
        if specs and self._enable_compute_groups and not self._groups_checked:
            args, kwargs = specs[0]
            self.resolve_compute_groups(*args, **kwargs)
            self._compute_groups_create_state_ref()
        ex = self._get_executor()
        if ex is None:
            return {"warmed": 0, "already_warm": 0, "skipped": ["executor disabled"], "seconds": 0.0}
        return ex.warmup(specs, forward=forward, ladder=ladder, background=background)

    def warmup_from_manifest(self, manifest: Any, background: bool = False) -> Any:
        """Replay a shape-profile manifest (dict from :meth:`shape_profile` or
        a path written by :meth:`save_shape_profile`): precompiles exactly the
        fused buckets a previous run recorded."""
        from torchmetrics_tpu.ops import compile_cache

        if isinstance(manifest, (str, os.PathLike)):
            manifest = compile_cache.load_shape_manifest(os.fspath(manifest))
        specs = manifest.get("specs") or []
        if specs and self._enable_compute_groups and not self._groups_checked:
            args, kwargs = compile_cache.dummy_from_spec(specs[0])
            self.resolve_compute_groups(*args, **kwargs)
            self._compute_groups_create_state_ref()
        ex = self._get_executor()
        if ex is None:
            return {"warmed": 0, "already_warm": 0, "skipped": ["executor disabled"], "seconds": 0.0}
        return ex.warmup_from_manifest(manifest, background=background)

    def shape_profile(self) -> Dict[str, Any]:
        """Replayable manifest of the fused call shapes this collection's
        executor has served (see :meth:`Metric.shape_profile`)."""
        ex = self._get_executor()
        if ex is None:
            from torchmetrics_tpu.ops.compile_cache import PROFILE_VERSION

            return {"profile_version": PROFILE_VERSION, "owner": type(self).__name__, "specs": []}
        return ex.shape_profile()

    def save_shape_profile(self, path: str) -> str:
        """Atomically persist :meth:`shape_profile` as JSON at ``path``."""
        from torchmetrics_tpu.ops.compile_cache import save_shape_manifest

        return save_shape_manifest(path, self.shape_profile())

    def set_background_compile(self, enabled: Optional[bool]) -> None:
        """Override stall-free background compilation for the fused executor
        AND every member's (cold keys dispatch eagerly while compiles run on
        the worker; ``None`` restores the env default)."""
        ex = self._get_executor()
        if ex is not None:
            ex.set_background_compile(enabled)
        for m in self._modules.values():
            m.set_background_compile(enabled)

    # ------------------------------------------------- sharded (deferred) API
    def init_sharded_states(self, num_shards: int) -> Dict[str, Dict[str, Any]]:
        """Fresh states in the sharded layout (leading shard axis on every
        field, one pytree per group leader) — the carry of a deferred-reduction
        epoch loop (docs/SHARDING.md)."""
        return init_sharded_states(self.functional_init(), num_shards)

    def sharded_state_spec(self, axis_name: str = "batch") -> Dict[str, Any]:
        """PartitionSpec pytree partitioning every field's leading shard axis
        along ``axis_name`` — the ``shard_map`` in/out spec of the collection's
        local-accumulation step."""
        return local_accumulate_spec(self.functional_init(), axis_name)

    def reduce_sharded_states(
        self, states: Dict[str, Dict[str, Any]], axis_name: Optional[Union[str, Sequence[str]]] = None
    ) -> Dict[str, Dict[str, Any]]:
        """The deferred-reduction read point for the whole collection, inside a
        ``shard_map`` body: drop the local shard axis and run
        :meth:`functional_sync` once — the cross-group leaf fusion folds every
        sum-family field of EVERY compute group into one collective rendezvous
        per (reduction, dtype), instead of one per field per step."""
        from torchmetrics_tpu import obs

        with obs.device_span(obs.SPAN_REDUCE):
            return self.functional_sync(unshard_local_state(states), axis_name)

    def reshard_states(self, states: Dict[str, Dict[str, Any]], to_num_shards: int) -> Dict[str, Dict[str, Any]]:
        """Re-split every group leader's stacked sharded state onto
        ``to_num_shards`` via :meth:`Metric.reshard_state` — the collection
        face of the audited ``parallel/reshard.py`` seam (elastic restore of
        a mid-epoch deferred checkpoint onto a resized mesh)."""
        return {
            leader: self._modules[leader].reshard_state(sub, to_num_shards)
            for leader, sub in states.items()
        }

    def functional_update(self, states: Dict[str, Dict[str, Any]], *args: Any, **kwargs: Any) -> Dict[str, Dict[str, Any]]:
        """Pure update: one leader ``functional_update`` per compute group.

        The ``shared_scope`` makes this call the megakernel fusion unit: every
        leader sees the same batch tracers, so classification-family groups
        resolve their counting cores to ONE shared kernel result for the
        duration of this call (ops/fused_classification.py); the scope pops
        with the call, so traced intermediates never outlive their trace."""
        from torchmetrics_tpu.ops.kernels import shared_scope

        out: Dict[str, Dict[str, Any]] = {}
        with shared_scope():
            for cg in self._groups.values():
                m0 = self._modules[cg[0]]
                out[cg[0]] = m0.functional_update(states[cg[0]], *args, **m0._filter_kwargs(**kwargs))
        return out

    def functional_sync(
        self, states: Dict[str, Dict[str, Any]], axis_name: Optional[Union[str, Sequence[str]]] = None
    ) -> Dict[str, Dict[str, Any]]:
        """Pure in-trace sync with cross-group collective fusion.

        Same-reduction fields are fused across ALL compute groups sharing a sync
        axis, so a whole collection of sum-reduced metrics costs ONE ``lax.psum``
        rendezvous per step rather than one per group (``sync_states`` already
        fuses within a metric; this lifts the fusion to the collection level).
        Leaders with a custom ``dist_sync_fn`` keep their own path.

        Like :meth:`Metric.functional_sync`, the reserved ``"_update_count"``
        key carried by :meth:`state` exports is stripped from the collectives
        and re-attached summed across ranks.
        """
        import jax

        count_key = Metric._STATE_COUNT_KEY
        out: Dict[str, Dict[str, Any]] = {}
        # leaders fusable together must resolve to the same mesh axis
        by_axis: Dict[Any, List[str]] = {}
        for leader, st in states.items():
            m = self._modules[leader]
            # only fuse plain Metric sync paths: a custom dist_sync_fn or a
            # subclass/wrapper functional_sync override (BootStrapper, Running,
            # ClasswiseWrapper, ...) must keep its own semantics
            if m.dist_sync_fn is not None or type(m).functional_sync is not Metric.functional_sync:
                out[leader] = m.functional_sync(st, axis_name)
                continue
            axis = axis_name or m.sync_axis
            key = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            by_axis.setdefault(key, []).append(leader)
        for axis_key, leaders in by_axis.items():
            axis = list(axis_key) if isinstance(axis_key, tuple) else axis_key
            flat = {
                f"{leader}\x00{field}": v
                for leader in leaders
                for field, v in states[leader].items()
                if field != count_key
            }
            reds = {
                f"{leader}\x00{field}": self._modules[leader]._reductions.get(field)
                for leader in leaders
                for field in states[leader]
                if field != count_key
            }
            # each leader's resolved sync_precision rides into the fused call:
            # the qspec joins the group key inside sync_states, so a quantized
            # member fuses only with same-(bits, block) peers and an exact
            # member's psum arithmetic is never perturbed
            qspecs = {
                f"{leader}\x00{field}": spec
                for leader in leaders
                for field, spec in self._modules[leader]._sync_qspecs().items()
            }
            synced = sync_states(flat, reds, axis, qspecs=qspecs)
            for leader in leaders:
                out[leader] = {
                    field: synced[f"{leader}\x00{field}"] for field in states[leader] if field != count_key
                }
                if count_key in states[leader]:
                    out[leader][count_key] = jax.lax.psum(jnp.asarray(states[leader][count_key]), axis)
        return out

    def functional_compute(self, states: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        """Pure compute: every member reads its group leader's state; results are
        flattened/renamed exactly like :meth:`compute`."""
        result: Dict[str, Any] = {}
        for cg in self._groups.values():
            st = states[cg[0]]
            for name in cg:
                result[name] = self._modules[name].functional_compute(st)
        return self._flatten_results(result)

    def state(self) -> Dict[str, Dict[str, Any]]:
        """Live states in the functional layout: one pytree per group leader
        (followers share the leader's state, reference collections.py:289-308)."""
        return {cg[0]: self._modules[cg[0]].state() for cg in self._groups.values()}

    def state_spec(self) -> Dict[str, Any]:
        """Per-group-leader :meth:`Metric.state_spec`, exported alongside
        :meth:`state` so checkpointing layers can verify a restore target."""
        return {cg[0]: self._modules[cg[0]].state_spec() for cg in self._groups.values()}

    @property
    def executor_status(self) -> Dict[str, Any]:
        """Fused-executor diagnosis for the collection plus per-member status
        (see :attr:`Metric.executor_status`)."""
        from torchmetrics_tpu.ops.executor import executor_enabled_default, executor_stats
        from torchmetrics_tpu.ops.kernels import gate_snapshot

        enabled = self._executor_enabled
        enabled = executor_enabled_default() if enabled is None else enabled
        stats = executor_stats(self)
        return {
            "enabled": enabled,
            "engaged": stats["calls"] > 0,
            "fallback_reason": None if enabled is False else stats.get("fallback_reason"),
            "deferred_pending": any(m.deferred_pending for m in self._modules.values()),
            "stats": stats,
            # last gate decision per backend-dispatched kernel (ISSUE 11);
            # process-global, duplicated per member under members[...]
            "kernels": gate_snapshot(),
            "members": {name: m.executor_status for name, m in self._modules.items()},
        }

    def load_state(
        self,
        states: Dict[str, Dict[str, Any]],
        update_count: Optional[int] = None,
        validate: str = "strict",
        check_finite: bool = False,
        sharded: Optional[bool] = None,
    ) -> None:
        """Install leader-keyed state pytrees into every member of each group.

        The saved keys reflect the SOURCE collection's resolved groups, which
        may be coarser than this collection's (e.g. saved after auto-grouping,
        loaded into a fresh collection still holding singleton groups). A
        target leader missing from ``states`` falls back to the unique saved
        state whose field names/shapes/dtypes match its own defaults; genuine
        ambiguity raises."""

        def _sig_of_state(st: Dict[str, Any]) -> tuple:
            return tuple(
                sorted(
                    (k, getattr(v, "shape", None), str(getattr(v, "dtype", "")))
                    for k, v in st.items()
                    if k not in Metric._RESERVED_STATE_KEYS  # count/shard markers are not state fields
                )
            )

        for cg in self._groups.values():
            if cg[0] in states:
                st = states[cg[0]]
            else:
                want = _sig_of_state(self._modules[cg[0]].functional_init())
                cands = [k for k, v in states.items() if _sig_of_state(v) == want]
                if len(cands) != 1:
                    raise KeyError(
                        f"state missing group leader {cg[0]!r} and"
                        f" {'no' if not cands else 'multiple'} saved states match its layout"
                        f" (candidates: {cands}); save and load with the same compute-group"
                        " resolution to disambiguate"
                    )
                st = states[cands[0]]
                # the match is structural only (field names/shapes/dtypes) — a
                # state saved from a different collection whose single entry
                # happens to share the layout would load silently. The expected
                # fallback case is a same-collection topology change (saved
                # after auto-grouping, loaded into singleton groups): there the
                # matched key names a member of THIS collection. An unknown key
                # means the states came from somewhere else — make that visible.
                if cands[0] not in self._modules:
                    rank_zero_warn(
                        f"load_state: group leader {cg[0]!r} not in saved states; matched saved"
                        f" state {cands[0]!r} (not a member of this collection) by field-layout"
                        " signature only. Verify the states were saved from an equivalent"
                        " collection."
                    )
            for name in cg:
                member = self._modules[name]
                if type(member).load_state is Metric.load_state:
                    member.load_state(
                        st, update_count=update_count, validate=validate, check_finite=check_finite, sharded=sharded
                    )
                else:
                    # wrappers override load_state with their own layouts (and
                    # signatures); forward only the knobs the override accepts
                    # (LanedMetric keeps the full validated signature; older
                    # wrappers validate structurally themselves)
                    import inspect

                    params = inspect.signature(member.load_state).parameters
                    extra = {
                        k: v
                        for k, v in (("validate", validate), ("check_finite", check_finite), ("sharded", sharded))
                        if k in params
                    }
                    member.load_state(st, update_count=update_count, **extra)

    def merge_states(
        self,
        a: Dict[str, Dict[str, Any]],
        b: Dict[str, Dict[str, Any]],
        counts: Optional[Tuple[int, int]] = None,
    ) -> Dict[str, Dict[str, Any]]:
        """Merge two collection state pytrees per each leader's declared
        reductions (the collection analogue of :meth:`Metric.merge_states`)."""
        return {leader: self._modules[leader].merge_states(a[leader], b[leader], counts=counts) for leader in a}

    def functional_forward(
        self, states: Dict[str, Dict[str, Any]], *args: Any, update_count: Optional[int] = None, **kwargs: Any
    ) -> Tuple[Dict[str, Dict[str, Any]], Dict[str, Any]]:
        """Pure forward: ``(states, batch) -> (states', batch_values)``.

        One leader update per group; each member's batch value derives from the
        leader's batch state; the batch state merges into the accumulated state
        via the leader's declared reductions. As with
        :meth:`Metric.functional_forward`, pass ``update_count`` (the number of
        updates already merged into ``states``) so ``"mean"``-reduced states
        merge count-weighted.
        """
        from torchmetrics_tpu.ops.kernels import shared_scope

        new_states: Dict[str, Dict[str, Any]] = {}
        result: Dict[str, Any] = {}
        counts = (update_count, 1) if update_count is not None else None
        with shared_scope():
            return self._functional_forward_in_scope(states, new_states, result, counts, args, kwargs)

    def _functional_forward_in_scope(self, states, new_states, result, counts, args, kwargs):
        for cg in self._groups.values():
            m0 = self._modules[cg[0]]
            if type(m0).functional_forward is not Metric.functional_forward:
                # a leader with its own forward semantics (MinMaxMetric's extrema
                # fold, Running's window shift) must run them; wrapper trial
                # states never structurally match plain metrics, so such a
                # leader is always alone in its group. No update_count: these
                # wrappers carry their own counts in-state.
                new_states[cg[0]], result[cg[0]] = m0.functional_forward(
                    states[cg[0]], *args, **m0._filter_kwargs(**kwargs)
                )
                continue
            batch_state = m0.functional_update(m0.functional_init(), *args, **m0._filter_kwargs(**kwargs))
            new_states[cg[0]] = m0.merge_states(states[cg[0]], batch_state, counts=counts)
            for name in cg:
                result[name] = self._modules[name].functional_compute(batch_state)
        return new_states, self._flatten_results(result)

    def reset(self) -> None:
        for m in self._modules.values():
            m.reset()
        if self._enable_compute_groups and self._groups_checked:
            self._compute_groups_create_state_ref()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix is not None:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix is not None:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self._modules.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, m in self._modules.items():
            m.state_dict(out, prefix=f"{k}.")
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        for k, m in self._modules.items():
            m.load_state_dict(state_dict, prefix=f"{k}.", strict=strict)

    def to(self, device) -> "MetricCollection":
        for m in self._modules.values():
            m.to(device)
        return self

    def sync(self, **kwargs: Any) -> None:
        for m in self._modules.values():
            m.sync(**kwargs)

    def unsync(self, **kwargs: Any) -> None:
        for m in self._modules.values():
            m.unsync(**kwargs)

    def set_dtype(self, dst_type) -> "MetricCollection":
        """Cast every member's states (reference collections.py:582 analogue)."""
        for m in self._modules.values():
            m.set_dtype(dst_type)
        return self

    def plot(self, val: Optional[Dict[str, Any]] = None, ax: Any = None, together: bool = False):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax)

    def laned(self, capacity: int = 8, max_capacity: Optional[int] = None, **kwargs: Any) -> Any:
        """A :class:`~torchmetrics_tpu.lanes.LanedCollection` holding N
        independent copies of every member's state, all sharing one
        session→lane table — the whole suite advances per traffic round with
        one fused dispatch (docs/LANES.md)."""
        from torchmetrics_tpu.lanes import LanedCollection

        return LanedCollection(self, capacity=capacity, max_capacity=max_capacity, **kwargs)

    def windowed(self, window: int = 8, lateness: int = 0, **kwargs: Any) -> Any:
        """A :class:`~torchmetrics_tpu.windows.WindowedCollection` stacking W
        per-window copies of every member's state on a ring axis — the whole
        suite advances its tumbling/sliding windows in O(1) per close
        (docs/STREAMING.md)."""
        from torchmetrics_tpu.windows import WindowedCollection

        return WindowedCollection(self, window=window, lateness=lateness, **kwargs)

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        return self._groups

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for k, v in self._modules.items():
            repr_str += f"\n  {k}: {v!r},"
        return repr_str + "\n)"
