"""Modular ASR error-rate metrics: WER, CER, MER, WIL, WIP.

Reference: text/{wer,cer,mer,wil,wip}.py — two/three scalar sum states.
"""
from __future__ import annotations

from typing import Any, List, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.asr import (
    _cer_compute,
    _cer_update,
    _mer_compute,
    _mer_update,
    _wer_compute,
    _wer_update,
    _wil_compute,
    _wip_compute,
    _word_info_update,
)
from torchmetrics_tpu.metric import Metric


class WordErrorRate(Metric):
    """Word error rate (reference text/wer.py:28).

    Example:
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> wer = WordErrorRate()
        >>> wer.update(["this is the answer", "hello duck"],
        ...            ["this was the answer", "hello world"])
        >>> round(float(wer.compute()), 4)
        0.3333
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)


class CharErrorRate(Metric):
    """Character error rate (reference text/cer.py:28).

    Example:
        >>> from torchmetrics_tpu.text import CharErrorRate
        >>> cer = CharErrorRate()
        >>> cer.update(["this is the answer", "hello duck"],
        ...            ["this was the answer", "hello world"])
        >>> round(float(cer.compute()), 4)
        0.2333
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _cer_compute(self.errors, self.total)


class MatchErrorRate(Metric):
    """Match error rate (reference text/mer.py:28).

    Example:
        >>> from torchmetrics_tpu.text import MatchErrorRate
        >>> mer = MatchErrorRate()
        >>> mer.update(["this is the answer", "hello duck"],
        ...            ["this was the answer", "hello world"])
        >>> round(float(mer.compute()), 4)
        0.3333
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _mer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)


class WordInfoLost(Metric):
    """Word information lost (reference text/wil.py:27).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoLost
        >>> wil = WordInfoLost()
        >>> wil.update(["this is the answer", "hello duck"],
        ...            ["this was the answer", "hello world"])
        >>> round(float(wil.compute()), 4)
        0.5556
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _word_info_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wil_compute(self.errors, self.target_total, self.preds_total)


class WordInfoPreserved(Metric):
    """Word information preserved (reference text/wip.py:27).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoPreserved
        >>> wip = WordInfoPreserved()
        >>> wip.update(["this is the answer", "hello duck"],
        ...            ["this was the answer", "hello world"])
        >>> round(float(wip.compute()), 4)
        0.4444
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _word_info_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)
