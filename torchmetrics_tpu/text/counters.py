"""Modular n-gram / edit-distance text metrics: BLEU, SacreBLEU, CHRF, TER,
EditDistance, ExtendedEditDistance.

Reference: text/{bleu,sacre_bleu,chrf,ter,edit,eed}.py. All states are dense
jnp accumulators (sum) or cat list states — psum/all-gather syncable.
"""
from __future__ import annotations

from functools import partial
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.text.bleu import (
    _bleu_score_compute,
    _bleu_score_update,
    _SacreBLEUTokenizer,
    _tokenize_fn,
    AVAILABLE_TOKENIZERS,
)
from torchmetrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from torchmetrics_tpu.functional.text.edit import (
    _edit_distance_compute,
    _edit_distance_update,
    _eed_compute,
    _eed_update,
)
from torchmetrics_tpu.functional.text.ter import _ter_compute, _ter_update, _TercomTokenizer
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class BLEUScore(Metric):
    """BLEU (reference text/bleu.py:33).

    Example:
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> bleu = BLEUScore()
        >>> bleu.update(["the cat sat on the mat"], [["a cat sat on the mat"]])
        >>> round(float(bleu.compute()), 4)
        0.7598
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self.tokenizer = _tokenize_fn

        self.add_state("preds_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        self.preds_len, self.target_len, self.numerator, self.denominator = _bleu_score_update(
            preds_, target_, self.numerator, self.denominator, self.preds_len, self.target_len,
            self.n_gram, self.tokenizer,
        )

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator,
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """SacreBLEU (reference text/sacre_bleu.py:34) — BLEU + standardized tokenizers.

    Example:
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> bleu = SacreBLEUScore(tokenize="13a")
        >>> bleu.update(["the cat sat on the mat"], [["a cat sat on the mat"]])
        >>> round(float(bleu.compute()), 4)
        0.7598
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self.tokenizer = partial(_SacreBLEUTokenizer.tokenize, tokenize=tokenize, lowercase=lowercase)

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        super().update(preds, target)


class CHRFScore(Metric):
    """chrF/chrF++ (reference text/chrf.py:52).

    State layout redesign: six dense per-order vectors instead of the
    reference's 6×order scalar dict states — one psum each.

    Example:
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> chrf = CHRFScore()
        >>> chrf.update(["the cat sat on the mat"], [["a cat sat on the mat"]])
        >>> round(float(chrf.compute()), 4)
        0.8713
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)

        self.add_state("total_preds_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_preds_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_target_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_target_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_matching_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_matching_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_chrf_score", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        sentence_scores: Optional[List[Array]] = [] if self.return_sentence_level_score else None
        (
            self.total_preds_char_n_grams, self.total_preds_word_n_grams,
            self.total_target_char_n_grams, self.total_target_word_n_grams,
            self.total_matching_char_n_grams, self.total_matching_word_n_grams,
            sentence_scores,
        ) = _chrf_score_update(
            preds, target,
            self.total_preds_char_n_grams, self.total_preds_word_n_grams,
            self.total_target_char_n_grams, self.total_target_word_n_grams,
            self.total_matching_char_n_grams, self.total_matching_word_n_grams,
            self.n_char_order, self.n_word_order, self.n_order,
            self.beta, self.lowercase, self.whitespace, sentence_scores,
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_chrf_score = list(self.sentence_chrf_score) + sentence_scores

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        corpus = _chrf_score_compute(
            self.total_preds_char_n_grams, self.total_preds_word_n_grams,
            self.total_target_char_n_grams, self.total_target_word_n_grams,
            self.total_matching_char_n_grams, self.total_matching_word_n_grams,
            self.n_order, self.beta,
        )
        if self.return_sentence_level_score:
            return corpus, dim_zero_cat([jnp.atleast_1d(s) for s in self.sentence_chrf_score])
        return corpus


class TranslationEditRate(Metric):
    """TER (reference text/ter.py:29).

    Example:
        >>> from torchmetrics_tpu.text import TranslationEditRate
        >>> ter = TranslationEditRate()
        >>> ter.update(["the cat sat on the mat"], [["a cat sat on the mat"]])
        >>> round(float(ter.compute()), 4)
        0.1667
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
        if not isinstance(no_punctuation, bool):
            raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
        if not isinstance(lowercase, bool):
            raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
        if not isinstance(asian_support, bool):
            raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", jnp.asarray(0.0), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        sentence_scores: Optional[List[Array]] = [] if self.return_sentence_level_score else None
        self.total_num_edits, self.total_tgt_length, sentence_scores = _ter_update(
            preds, target, self.tokenizer, self.total_num_edits, self.total_tgt_length, sentence_scores
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_ter = list(self.sentence_ter) + sentence_scores

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        corpus = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return corpus, dim_zero_cat([jnp.atleast_1d(s) for s in self.sentence_ter])
        return corpus


class EditDistance(Metric):
    """Levenshtein edit distance (reference text/edit.py:29).

    Example:
        >>> from torchmetrics_tpu.text import EditDistance
        >>> ed = EditDistance()
        >>> ed.update(["kitten"], ["sitting"])
        >>> float(ed.compute())
        3.0
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        allowed = ("mean", "sum", "none", None)
        if reduction not in allowed:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed}, but got {reduction}")
        self.substitution_cost = substitution_cost
        self.reduction = reduction

        if reduction == "none" or reduction is None:
            self.add_state("edit_scores_list", [], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        distance = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction == "none" or self.reduction is None:
            self.edit_scores_list = list(self.edit_scores_list) + [distance]
        else:
            self.edit_scores = self.edit_scores + distance.sum()
            self.num_elements = self.num_elements + distance.size

    def compute(self) -> Array:
        if self.reduction == "none" or self.reduction is None:
            if not self.edit_scores_list:
                return jnp.asarray(0, dtype=jnp.int32)
            return dim_zero_cat(self.edit_scores_list)
        return _edit_distance_compute(
            jnp.atleast_1d(self.edit_scores), self.num_elements, self.reduction
        )


class ExtendedEditDistance(Metric):
    """EED (reference text/eed.py:28).

    Example:
        >>> from torchmetrics_tpu.text import ExtendedEditDistance
        >>> eed = ExtendedEditDistance()
        >>> eed.update(["the cat sat on the mat"], [["a cat sat on the mat"]])
        >>> round(float(eed.compute()), 4)
        0.1452
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        for param, name in ((alpha, "alpha"), (rho, "rho"), (deletion, "deletion"), (insertion, "insertion")):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion
        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        scores = _eed_update(preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion)
        self.sentence_eed = list(self.sentence_eed) + scores

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        corpus = _eed_compute(list(self.sentence_eed))
        if self.return_sentence_level_score:
            return corpus, dim_zero_cat([jnp.atleast_1d(s) for s in self.sentence_eed])
        return corpus
